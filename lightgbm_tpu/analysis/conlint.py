"""Tier C of jaxlint: concurrency-discipline lint for the threaded
planes (``serving/``, ``continual/``, ``obs/``, ``robustness/``,
``native/``).

Tiers A/B guard the JAX hot paths and the compiled HLO; tier C guards
the *lock discipline* those paths run under.  It is pure-stdlib AST
analysis (importable without jax, like :mod:`.astlint`) in two passes:

pass 1 — per module, infer each class's lock fields
  (``self.x = threading.Lock()/RLock()/Condition()``, with
  ``Condition(self._lock)`` aliased to its base lock, plus
  module-level ``NAME = threading.Lock()`` globals) and record, per
  method, every lexical ``with <lock>:`` acquisition, every write /
  aggregate-read of a ``self.*`` field together with the lock set
  lexically held at that point, every intra-class and
  ``self.attr.method()`` call site, every ``cv.wait()`` and every
  potentially-blocking call.

pass 2 — resolve held-lock *inheritance* for private methods (a
  ``_method`` whose every intra-class call site holds lock L is
  analyzed as holding L — this is how ``# lock held by the caller``
  conventions like ``Telemetry._event`` stay pragma-free), then emit:

* **CL001** unguarded shared write/publish: a field written under a
  lock somewhere (its *owner* = the most common lock across its write
  sites) but written — or published via an aggregate read such as
  ``dict(self.f)`` / ``sorted(self.f.items())`` / iteration — without
  that owner held.  Single-key subscript/attribute/membership reads
  are deliberately NOT flagged: one ``dict.__getitem__`` is atomic
  under the GIL and pinning those would bury real findings in noise.
  ``__init__`` bodies are skipped (no concurrent peer exists yet) but
  nested ``def``/``lambda`` closures defined there ARE analyzed: they
  run later, on whatever thread fires them.
* **CL002** lock-order inversion: global acquired-while-holding
  digraph — edges from lexical nesting, from inherited held sets, and
  from cross-class calls (``self.registry.publish()`` under the
  service lock adds service-lock → every lock ``publish`` acquires;
  attribute types come from ``self.x = ClassName(...)`` and annotated
  ``__init__`` params) — then fails on every edge of every cycle.
  Re-acquiring an RLock/Condition you already hold is reentrant and
  skipped; a plain ``Lock`` self-edge is a guaranteed deadlock and
  flagged.
* **CL003** blocking call under a lexically-held lock: device
  dispatch (``.predict``, ``.block_until_ready``, dotted
  ``jax.``/``jnp.``/``lax.`` calls), ``time.sleep``, thread ``join``,
  ``subprocess.*``, ``open``/``shutil.rmtree``/``urlopen``/socket
  verbs — the pump's latency/deadlock trap.
* **CL004** ``cv.wait()`` on a Condition field with no enclosing
  ``while``: a wait whose predicate isn't re-checked swallows spurious
  wakeups and missed-notify races.

Findings key as ``RULE:path:qualname`` and ratchet against the
``tier_c`` table of ``jaxlint_baseline.json`` exactly like tier A
(new findings AND stale pins both fail).  Suppress a single line with
``# conlint: ok=CL001`` (comma list; bare ``ok`` silences every rule)
— every pragma must state the invariant that makes the site safe.

The dynamic half lives in :mod:`.schedule`: CL001 finding lines become
the extra yield points its cooperative scheduler interleaves at.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "RULES", "SCOPE", "lint_source", "lint_tree",
           "iter_scope_files", "finding_counts"]

RULES = {
    "CL001": "field guarded elsewhere is written/published without its owning lock",
    "CL002": "lock-order inversion (acquired-while-holding cycle)",
    "CL003": "blocking call inside a lexically-held lock",
    "CL004": "condition wait() without an enclosing predicate while-loop",
}

#: analysis scope, relative to the package root
SCOPE = ("serving/", "continual/", "obs/", "robustness/", "native/")

_PRAGMA_RE = re.compile(r"#\s*conlint:\s*(?:ok|disable)"
                        r"(?:\s*=\s*([A-Z0-9,\s]+))?")

_LOCK_CTORS = {"threading.Lock": "lock", "Lock": "lock",
               "threading.RLock": "rlock", "RLock": "rlock",
               "threading.Condition": "condition", "Condition": "condition"}

#: container methods that mutate their receiver in place
_MUTATORS = {"append", "appendleft", "add", "update", "pop", "popitem",
             "popleft", "clear", "remove", "discard", "extend", "insert",
             "setdefault", "move_to_end", "rotate"}

#: builtins that *publish* a whole container (multi-element read)
_AGG_CALLS = {"dict", "list", "sorted", "tuple", "set", "frozenset",
              "sum", "max", "min"}
_VIEW_METHODS = {"items", "values", "keys", "copy", "most_common"}

_BLOCKING_EXACT = {"time.sleep", "sleep", "open",
                   "subprocess.run", "subprocess.check_call",
                   "subprocess.check_output", "subprocess.Popen",
                   "shutil.rmtree", "os.replace", "urllib.request.urlopen"}
_BLOCKING_ATTRS = {"block_until_ready", "predict", "recv", "send",
                   "sendall", "accept", "connect", "urlopen"}
_BLOCKING_PREFIXES = ("jax.", "jnp.", "lax.")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str                   # package-relative, e.g. lightgbm_tpu/serving/service.py
    line: int
    col: int
    func: str                   # qualname, e.g. ServingService.stats
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.func}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message} [{self.func}]")

    def to_json(self) -> str:
        return json.dumps({
            "tier": "C", "rule": self.rule, "title": RULES[self.rule],
            "path": self.path, "line": self.line, "col": self.col,
            "func": self.func, "message": self.message, "key": self.key,
        }, sort_keys=True)


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _pragmas(source: str) -> Dict[int, Optional[Set[str]]]:
    """lineno -> suppressed rule set (None = all rules)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, ln in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(ln)
        if not m:
            continue
        if m.group(1):
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
        else:
            out[i] = None
    return out


def _self_field(node: ast.AST) -> Optional[str]:
    """``self.f`` / ``self.f[...]`` / ``self.f.attr`` -> ``f``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    chain: List[str] = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and chain:
        return chain[-1]            # first attribute after ``self``
    return None


# ---------------------------------------------------------------------------
# pass 1: per-module collection

@dataclass
class _Access:
    field: str
    kind: str                   # "write" | "readagg"
    held: Tuple[str, ...]       # lexical held set (normalized lock names)
    line: int
    col: int
    init: bool                  # event sits directly in __init__'s body


@dataclass
class _Acquire:
    lock: str                   # normalized node name (Class.attr or mod:NAME)
    lockkind: str               # lock | rlock | condition
    held: Tuple[str, ...]
    line: int
    col: int


@dataclass
class _Call:
    target: str                 # method name (intra-class) or "attr.method"
    attr: Optional[str]         # self attr for cross-class calls, else None
    held: Tuple[str, ...]
    line: int


@dataclass
class _MethodInfo:
    qualname: str
    name: str
    is_init_body: bool
    accesses: List[_Access] = field(default_factory=list)
    acquires: List[_Acquire] = field(default_factory=list)
    calls: List[_Call] = field(default_factory=list)
    waits: List[Tuple[str, int, int, bool]] = field(default_factory=list)
    blocking: List[Tuple[str, str, int, int]] = field(default_factory=list)
    inherited: Tuple[str, ...] = ()


@dataclass
class _ClassInfo:
    name: str
    path: str
    locks: Dict[str, str] = field(default_factory=dict)      # attr -> kind
    cond_base: Dict[str, str] = field(default_factory=dict)  # cond attr -> lock attr
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> ClassName
    methods: Dict[str, _MethodInfo] = field(default_factory=dict)


@dataclass
class _ModuleInfo:
    path: str
    pragmas: Dict[int, Optional[Set[str]]]
    module_locks: Dict[str, str] = field(default_factory=dict)  # NAME -> kind
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    functions: Dict[str, _MethodInfo] = field(default_factory=dict)


def _lock_ctor_kind(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d in _LOCK_CTORS:
            return _LOCK_CTORS[d]
    return None


class _FuncScan:
    """Walks one function/method body tracking the lexical held-lock
    set, ``while`` depth, and collecting events into a _MethodInfo.
    Nested defs/lambdas restart with an empty held set (they run
    later, on an unknown thread)."""

    def __init__(self, cls: Optional[_ClassInfo], mod: _ModuleInfo,
                 info: _MethodInfo, sink: List[_MethodInfo]):
        self.cls = cls
        self.mod = mod
        self.info = info
        self.sink = sink

    # -- lock identity -----------------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[Tuple[str, str]]:
        """(normalized name, kind) when ``expr`` is a known lock."""
        f = _self_field(expr) if not isinstance(expr, ast.Subscript) else None
        if f is not None and self.cls is not None and f in self.cls.locks:
            kind = self.cls.locks[f]
            base = self.cls.cond_base.get(f, f)
            return f"{self.cls.name}.{base}", kind
        if isinstance(expr, ast.Name) and expr.id in self.mod.module_locks:
            return (f"{self.mod.path}:{expr.id}",
                    self.mod.module_locks[expr.id])
        return None

    # -- recursive statement walk ------------------------------------------
    def scan(self, body: Sequence[ast.stmt], held: Tuple[str, ...],
             while_depth: int) -> None:
        for st in body:
            self._stmt(st, held, while_depth)

    def _stmt(self, st: ast.stmt, held: Tuple[str, ...], wd: int) -> None:
        if isinstance(st, ast.With):
            add: List[str] = []
            for item in st.items:
                lk = self._lock_of(item.context_expr)
                if lk is not None:
                    name, kind = lk
                    self.info.acquires.append(
                        _Acquire(name, kind, held, item.context_expr.lineno,
                                 item.context_expr.col_offset))
                    if name not in held:
                        add.append(name)
                else:
                    self._expr(item.context_expr, held, wd)
            self.scan(st.body, held + tuple(add), wd)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._nested(st.name, st.body, st.lineno)
            return
        if isinstance(st, ast.While):
            self._expr(st.test, held, wd)
            self.scan(st.body, held, wd + 1)
            self.scan(st.orelse, held, wd)
            return
        if isinstance(st, ast.For):
            self._read_target(st.iter, held, st)
            self._expr(st.iter, held, wd)
            self.scan(st.body, held, wd)
            self.scan(st.orelse, held, wd)
            return
        if isinstance(st, (ast.If,)):
            self._expr(st.test, held, wd)
            self.scan(st.body, held, wd)
            self.scan(st.orelse, held, wd)
            return
        if isinstance(st, ast.Try):
            self.scan(st.body, held, wd)
            for h in st.handlers:
                self.scan(h.body, held, wd)
            self.scan(st.orelse, held, wd)
            self.scan(st.finalbody, held, wd)
            return
        if isinstance(st, ast.Assign):
            for tgt in st.targets:
                self._write_target(tgt, held, st)
            self._expr(st.value, held, wd)
            return
        if isinstance(st, ast.AugAssign):
            self._write_target(st.target, held, st)
            self._expr(st.value, held, wd)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._write_target(st.target, held, st)
                self._expr(st.value, held, wd)
            return
        if isinstance(st, ast.Delete):
            for tgt in st.targets:
                self._write_target(tgt, held, st)
            return
        if isinstance(st, (ast.Expr, ast.Return)):
            val = st.value
            if val is not None:
                self._expr(val, held, wd)
            return
        # generic: walk child statements/expressions conservatively
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.stmt):
                self._stmt(child, held, wd)
            elif isinstance(child, ast.expr):
                self._expr(child, held, wd)

    def _nested(self, name: str, body: Sequence[ast.stmt],
                lineno: int) -> None:
        sub = _MethodInfo(qualname=f"{self.info.qualname}.{name}",
                          name=name, is_init_body=False)
        self.sink.append(sub)
        _FuncScan(self.cls, self.mod, sub, self.sink).scan(body, (), 0)

    # -- events ------------------------------------------------------------
    def _record(self, fieldname: str, kind: str, held: Tuple[str, ...],
                node: ast.AST) -> None:
        self.info.accesses.append(
            _Access(fieldname, kind, held, node.lineno, node.col_offset,
                    self.info.is_init_body))

    def _write_target(self, tgt: ast.AST, held: Tuple[str, ...],
                      at: ast.stmt) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._write_target(el, held, at)
            return
        f = _self_field(tgt)
        if f is not None and (self.cls is None or f not in self.cls.locks):
            self._record(f, "write", held, tgt)

    def _read_target(self, it: ast.AST, held: Tuple[str, ...],
                     at: ast.stmt) -> None:
        f = self._container_of(it)
        if f is not None:
            self._record(f, "readagg", held, it)

    def _container_of(self, node: ast.AST) -> Optional[str]:
        """``self.f`` or ``self.f.items()/values()/keys()/copy()``."""
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _VIEW_METHODS:
            node = node.func.value
        f = _self_field(node)
        if f is not None and (self.cls is None or f not in self.cls.locks):
            return f
        return None

    def _expr(self, e: ast.expr, held: Tuple[str, ...], wd: int) -> None:
        if isinstance(e, ast.Lambda):
            self._nested("<lambda>", [ast.Expr(value=e.body)], e.lineno)
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            for gen in e.generators:
                self._read_target(gen.iter, held, None)  # type: ignore[arg-type]
                self._expr(gen.iter, held, wd)
                for cond in gen.ifs:
                    self._expr(cond, held, wd)
            if isinstance(e, ast.DictComp):
                self._expr(e.key, held, wd)
                self._expr(e.value, held, wd)
            else:
                self._expr(e.elt, held, wd)
            return
        if isinstance(e, ast.Call):
            self._call(e, held, wd)
            for a in e.args:
                self._expr(a, held, wd)
            for kw in e.keywords:
                self._expr(kw.value, held, wd)
            if not isinstance(e.func, (ast.Name, ast.Attribute)):
                self._expr(e.func, held, wd)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, held, wd)

    def _call(self, e: ast.Call, held: Tuple[str, ...], wd: int) -> None:
        d = _dotted(e.func)
        fn = e.func
        # aggregate publish: dict(self.f) / sorted(self.f.items()) ...
        if isinstance(fn, ast.Name) and fn.id in _AGG_CALLS and e.args:
            f = self._container_of(e.args[0])
            if f is not None:
                self._record(f, "readagg", held, e)
        # mutator write: self.f.append(x) ...
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            f = _self_field(fn.value)
            if f is not None and (self.cls is None
                                  or f not in self.cls.locks):
                self._record(f, "write", held, e)
        # condition wait
        if isinstance(fn, ast.Attribute) and fn.attr == "wait":
            f = _self_field(fn.value)
            if (f is not None and self.cls is not None
                    and self.cls.locks.get(f) == "condition"):
                self.info.waits.append((f, e.lineno, e.col_offset, wd > 0))
        # blocking calls (lexically under a lock only)
        if held:
            self._blocking(e, d, held)
        # call-graph edges
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.info.calls.append(_Call(fn.attr, None, held, e.lineno))
            else:
                f = _self_field(recv)
                if f is not None and not isinstance(recv, ast.Subscript):
                    self.info.calls.append(
                        _Call(f"{f}.{fn.attr}", f, held, e.lineno))

    def _blocking(self, e: ast.Call, d: Optional[str],
                  held: Tuple[str, ...]) -> None:
        what: Optional[str] = None
        if d is not None and d in _BLOCKING_EXACT:
            what = d
        elif d is not None and d.startswith(_BLOCKING_PREFIXES):
            what = d
        elif isinstance(e.func, ast.Attribute):
            attr = e.func.attr
            if attr in _BLOCKING_ATTRS:
                what = f".{attr}()"
            elif attr == "join" and not e.args:
                # str.join always takes a positional iterable; a bare
                # join() / join(timeout=...) is a thread join
                what = ".join()"
        if what is not None:
            self.info.blocking.append((what, ",".join(held),
                                       e.lineno, e.col_offset))


def _collect_module(source: str, relpath: str) -> Optional[_ModuleInfo]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    mod = _ModuleInfo(path=relpath, pragmas=_pragmas(source))
    # module-level locks
    for st in tree.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            kind = _lock_ctor_kind(st.value)
            if kind is not None:
                mod.module_locks[st.targets[0].id] = kind
    for st in tree.body:
        if isinstance(st, ast.ClassDef):
            mod.classes[st.name] = _collect_class(st, mod, relpath)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _MethodInfo(qualname=st.name, name=st.name,
                               is_init_body=False)
            sink: List[_MethodInfo] = [info]
            _FuncScan(None, mod, info, sink).scan(st.body, (), 0)
            for mi in sink:
                mod.functions[mi.qualname] = mi
    return mod


def _collect_class(cd: ast.ClassDef, mod: _ModuleInfo,
                   relpath: str) -> _ClassInfo:
    ci = _ClassInfo(name=cd.name, path=relpath)
    # pre-pass: lock fields, condition aliases, attr types (any method)
    init_params: Dict[str, str] = {}
    for st in cd.body:
        if isinstance(st, ast.FunctionDef) and st.name == "__init__":
            for arg in st.args.args + st.args.kwonlyargs:
                if arg.annotation is not None:
                    ann = _dotted(arg.annotation)
                    if ann:
                        init_params[arg.arg] = ann.split(".")[-1]
    for node in ast.walk(cd):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        f = _self_field(node.targets[0])
        if f is None or not isinstance(node.targets[0], ast.Attribute):
            continue
        kind = _lock_ctor_kind(node.value)
        if kind is not None:
            ci.locks[f] = kind
            if kind == "condition" and isinstance(node.value, ast.Call) \
                    and node.value.args:
                base = _self_field(node.value.args[0])
                if base is not None:
                    ci.cond_base[f] = base
            continue
        if isinstance(node.value, ast.Call):
            d = _dotted(node.value.func)
            if d is not None:
                last = d.split(".")[-1]
                if last[:1].isupper():
                    ci.attr_types[f] = last
        elif isinstance(node.value, ast.Name) \
                and node.value.id in init_params:
            ci.attr_types[f] = init_params[node.value.id]
    # condition without alias: guard against dangling cond_base
    for f, base in list(ci.cond_base.items()):
        if base not in ci.locks:
            del ci.cond_base[f]
    # method bodies
    for st in cd.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _MethodInfo(qualname=f"{cd.name}.{st.name}",
                               name=st.name,
                               is_init_body=(st.name == "__init__"))
            sink: List[_MethodInfo] = [info]
            _FuncScan(ci, mod, info, sink).scan(st.body, (), 0)
            for mi in sink:
                ci.methods[mi.qualname] = mi
    return ci


# ---------------------------------------------------------------------------
# pass 2: inheritance fixpoint + rule emission

def _resolve_inherited(ci: _ClassInfo) -> None:
    """Private methods called only under lock L inherit L (intersection
    over intra-class call sites, to a fixpoint)."""
    by_name: Dict[str, List[_MethodInfo]] = {}
    for mi in ci.methods.values():
        by_name.setdefault(mi.name, []).append(mi)
    for _ in range(10):
        changed = False
        for mi in ci.methods.values():
            if not mi.name.startswith("_") or mi.name.startswith("__"):
                continue
            sites: List[Set[str]] = []
            for caller in ci.methods.values():
                for call in caller.calls:
                    if call.attr is None and call.target == mi.name:
                        sites.append(set(call.held)
                                     | set(caller.inherited))
            if not sites:
                continue
            new = sites[0]
            for s in sites[1:]:
                new &= s
            newt = tuple(sorted(new))
            # the same name can appear as several pseudo-methods
            # (nested defs); inheritance applies to the top-level one
            if newt != mi.inherited:
                mi.inherited = newt
                changed = True
        if not changed:
            break


class _Emitter:
    def __init__(self):
        self.findings: List[Finding] = []
        self._pragmas: Dict[str, Dict[int, Optional[Set[str]]]] = {}

    def register(self, mod: _ModuleInfo) -> None:
        self._pragmas[mod.path] = mod.pragmas

    def emit(self, rule: str, path: str, line: int, col: int,
             func: str, message: str) -> None:
        file_pragmas = self._pragmas.get(path, {})
        if line in file_pragmas:
            s = file_pragmas[line]
            if s is None or rule in s:
                return
        self.findings.append(Finding(rule, path, line, col, func, message))


def _effective(mi: _MethodInfo, held: Tuple[str, ...]) -> Set[str]:
    return set(held) | set(mi.inherited)


def _cl001(ci: _ClassInfo, em: _Emitter) -> None:
    if not ci.locks:
        return
    # gather per-field write/readagg events with effective held sets
    events: Dict[str, List[Tuple[str, Set[str], int, int, str, bool]]] = {}
    for mi in ci.methods.values():
        for ev in mi.accesses:
            events.setdefault(ev.field, []).append(
                (ev.kind, _effective(mi, ev.held), ev.line, ev.col,
                 mi.qualname, ev.init))
    for fieldname, evs in sorted(events.items()):
        writes = [e for e in evs if e[0] == "write" and not e[5]]
        guarded = [e for e in writes if e[1]]
        if not guarded:
            continue                    # never written under a lock: not ours
        # owner = most common lock across (non-init) write sites
        tally: Dict[str, int] = {}
        for _, held, *_rest in guarded:
            for lk in held:
                tally[lk] = tally.get(lk, 0) + 1
        owner = sorted(tally.items(), key=lambda kv: (-kv[1], kv[0]))[0][0]
        for kind, held, line, col, qual, init in evs:
            if init or owner in held:
                continue
            verb = ("written" if kind == "write"
                    else "published (aggregate read)")
            em.emit("CL001", ci.path, line, col, qual,
                    f"self.{fieldname} {verb} without {owner} "
                    f"(held elsewhere when writing it)")


def _cl003_cl004(ci_or_mod, methods: Iterable[_MethodInfo], path: str,
                 em: _Emitter) -> None:
    for mi in methods:
        for what, held, line, col in mi.blocking:
            em.emit("CL003", path, line, col, mi.qualname,
                    f"blocking call {what} while holding {held}")
        for f, line, col, in_while in mi.waits:
            if not in_while:
                em.emit("CL004", path, line, col, mi.qualname,
                        f"self.{f}.wait() outside a while predicate loop")


def _cl002(modules: List[_ModuleInfo], em: _Emitter) -> None:
    # class name -> _ClassInfo (global, for cross-class edges)
    classes: Dict[str, _ClassInfo] = {}
    for mod in modules:
        for ci in mod.classes.values():
            classes.setdefault(ci.name, ci)

    def lexical_locks(ci: _ClassInfo, method: str) -> Set[str]:
        out: Set[str] = set()
        mi = ci.methods.get(f"{ci.name}.{method}")
        if mi is not None:
            out.update(a.lock for a in mi.acquires)
        return out

    # edges: (src, dst) -> (path, line, qualname, detail)
    edges: Dict[Tuple[str, str], Tuple[str, int, str, str]] = {}

    def add_edge(src: str, dst: str, path: str, line: int,
                 qual: str, detail: str) -> None:
        if src == dst:
            return
        edges.setdefault((src, dst), (path, line, qual, detail))

    for mod in modules:
        for ci in mod.classes.values():
            for mi in ci.methods.values():
                for acq in mi.acquires:
                    heldset = _effective(mi, acq.held)
                    if acq.lock in heldset:
                        if acq.lockkind == "lock":
                            em.emit("CL002", ci.path, acq.line, acq.col,
                                    mi.qualname,
                                    f"non-reentrant {acq.lock} re-acquired "
                                    f"while already held (self-deadlock)")
                        continue
                    for h in sorted(heldset):
                        add_edge(h, acq.lock, ci.path, acq.line,
                                 mi.qualname,
                                 f"acquires {acq.lock} while holding {h}")
                for call in mi.calls:
                    if call.attr is None:
                        continue
                    heldset = _effective(mi, call.held)
                    if not heldset:
                        continue
                    tgt_cls = classes.get(ci.attr_types.get(call.attr, ""))
                    if tgt_cls is None:
                        continue
                    method = call.target.split(".", 1)[1]
                    for dst in sorted(lexical_locks(tgt_cls, method)):
                        for h in sorted(heldset):
                            if h == dst:
                                continue
                            add_edge(h, dst, ci.path, call.line,
                                     mi.qualname,
                                     f"calls {call.target}() (acquires "
                                     f"{dst}) while holding {h}")

    # cycle detection: iterative DFS over the digraph
    graph: Dict[str, List[str]] = {}
    for (src, dst) in edges:
        graph.setdefault(src, []).append(dst)
        graph.setdefault(dst, [])
    color: Dict[str, int] = {}
    cyclic_edges: Set[Tuple[str, str]] = set()

    def dfs(start: str) -> None:
        stack: List[Tuple[str, Iterable[str]]] = [(start, iter(sorted(graph[start])))]
        path: List[str] = [start]
        color[start] = 1
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if color.get(nxt, 0) == 1:      # back edge -> cycle
                    i = path.index(nxt)
                    cyc = path[i:] + [nxt]
                    for a, b in zip(cyc, cyc[1:]):
                        cyclic_edges.add((a, b))
                elif color.get(nxt, 0) == 0:
                    color[nxt] = 1
                    path.append(nxt)
                    stack.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
            if not advanced:
                color[node] = 2
                path.pop()
                stack.pop()

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            dfs(n)

    for (src, dst) in sorted(cyclic_edges):
        path, line, qual, detail = edges[(src, dst)]
        em.emit("CL002", path, line, 0, qual,
                f"lock-order inversion: edge {src} -> {dst} is part of a "
                f"cycle ({detail})")


def _analyze(modules: List[_ModuleInfo]) -> List[Finding]:
    em = _Emitter()
    for mod in modules:
        em.register(mod)
    for mod in modules:
        for ci in mod.classes.values():
            _resolve_inherited(ci)
    for mod in modules:
        for ci in mod.classes.values():
            _cl001(ci, em)
            _cl003_cl004(ci, ci.methods.values(), ci.path, em)
        _cl003_cl004(mod, mod.functions.values(), mod.path, em)
    _cl002(modules, em)
    return sorted(em.findings,
                  key=lambda f: (f.path, f.line, f.col, f.rule))


# ---------------------------------------------------------------------------
# entry points

def _in_scope(relpath: str, package_root: str = "lightgbm_tpu") -> bool:
    rel = relpath
    prefix = package_root.replace(os.sep, "/") + "/"
    if rel.startswith(prefix):
        rel = rel[len(prefix):]
    return rel.startswith(SCOPE)


def lint_source(source: str, path: str,
                package_root: str = "lightgbm_tpu") -> List[Finding]:
    """Lint one module's source.  ``path`` should be package-relative
    (``lightgbm_tpu/serving/service.py``); out-of-scope paths return []
    so tier A fixtures can share a test harness."""
    if not _in_scope(path, package_root):
        return []
    mod = _collect_module(source, path)
    if mod is None:
        return []
    return _analyze([mod])


def iter_scope_files(repo_root: str, package: str = "lightgbm_tpu"
                     ) -> Iterable[Tuple[str, str]]:
    pkg_dir = os.path.join(repo_root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, repo_root).replace(os.sep, "/")
            if _in_scope(rel, package):
                yield full, rel


def lint_tree(repo_root: str, package: str = "lightgbm_tpu"
              ) -> List[Finding]:
    """Cross-module lint of every in-scope file (the CL002 graph spans
    files: service -> registry edges need both sides)."""
    modules: List[_ModuleInfo] = []
    for full, rel in iter_scope_files(repo_root, package):
        with open(full, "r", encoding="utf-8") as fh:
            source = fh.read()
        mod = _collect_module(source, rel)
        if mod is not None:
            modules.append(mod)
    return _analyze(modules)


def finding_counts(findings: Iterable[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return dict(sorted(counts.items()))
