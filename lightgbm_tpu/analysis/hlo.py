"""Compile-artifact inspection: optimized-HLO parsing and op-count
extraction for the tree-build while-body and other entry points.

The per-split fixed cost of the tree loop is OP-COUNT bound, not
any-single-op bound (PERF.md round 2: 327 HLO ops / 32 copies in the
while body at ~1.5 us dispatch overhead each IS the 0.45 ms/split), so
bookkeeping-op regressions are perf regressions that the tunnel's noise
floor would otherwise hide.  This module compiles designated entry
points on the CURRENT backend, extracts computations from the optimized
HLO text, and counts instructions, fusions and copies — including
copies grouped by shape, which is how the round-4 "two contextual
f32[256,28,255,2] parent-hist copies per split" smoking gun was pinned.

Consumers: ``tools/hlo_report.py`` (CLI), ``tests/test_hlo_guard.py``
(tier-1 ceilings) and :mod:`lightgbm_tpu.analysis.artifacts` (the
jaxlint Tier B budget checks keyed to ``jaxlint_baseline.json``).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

__all__ = [
    "body_counts", "compile_tree_build", "entry_name", "report",
]


def _computation_blocks(hlo_text: str) -> Dict[str, List[str]]:
    """Split optimized HLO text into {computation_name: instruction
    lines} (top-level `name (...) -> ... {` blocks)."""
    blocks: Dict[str, List[str]] = {}
    cur = None
    head = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*{")
    for line in hlo_text.splitlines():
        if cur is None:
            m = head.match(line.strip())
            if m and line.rstrip().endswith("{"):
                cur = m.group(1)
                blocks[cur] = []
        elif line.strip() == "}":
            cur = None
        else:
            s = line.strip()
            if s and not s.startswith("//"):
                blocks[cur].append(s)
    return blocks


def entry_name(hlo_text: str) -> Optional[str]:
    """Name of the ENTRY computation, or None."""
    m = re.search(r"^ENTRY\s+%?([\w.\-]+)\s*\(", hlo_text, re.MULTILINE)
    return m.group(1) if m else None


def _while_bodies(hlo_text: str) -> List[str]:
    """Names of every while-loop body computation, outermost first by
    instruction count (the tree loop is the largest)."""
    names = set(re.findall(r"body=%?([\w.\-]+)", hlo_text))
    blocks = _computation_blocks(hlo_text)
    found = [n for n in names if n in blocks]
    return sorted(found, key=lambda n: -len(blocks[n]))


_OP_RE = re.compile(r"=\s*(?:[\w\[\],:{}\s/#*()$-]*?\s)?([a-z][\w-]*)\(")
_SHAPE_RE = re.compile(r"=\s*([a-z0-9]+\[[^\]]*\])(?:\{[^}]*\})?\s")


def body_counts(hlo_text: str, body_name: str = None) -> Dict[str, Any]:
    """Instruction/fusion/copy counts of one while-body computation
    (default: the largest, i.e. the tree loop)."""
    blocks = _computation_blocks(hlo_text)
    if body_name is None:
        bodies = _while_bodies(hlo_text)
        if not bodies:
            raise ValueError("no while body found in HLO text")
        body_name = bodies[0]
    lines = blocks[body_name]
    ops: Dict[str, int] = {}
    copies_by_shape: Dict[str, int] = {}
    for ln in lines:
        m = _OP_RE.search(ln)
        if not m:
            continue
        op = m.group(1)
        ops[op] = ops.get(op, 0) + 1
        if op == "copy":
            sm = _SHAPE_RE.search(ln)
            shape = sm.group(1) if sm else "?"
            copies_by_shape[shape] = copies_by_shape.get(shape, 0) + 1
    return {
        "body": body_name,
        "total_ops": sum(ops.values()),
        "fusions": ops.get("fusion", 0),
        "copies": ops.get("copy", 0),
        "whiles": ops.get("while", 0),
        "ops": dict(sorted(ops.items())),
        "copies_by_shape": dict(sorted(copies_by_shape.items(),
                                       key=lambda kv: -kv[1])),
    }


def compile_tree_build(params: Dict[str, Any] = None, n: int = 2048,
                       f: int = 10):
    """Compile one tree build on synthetic binned data and return the
    optimized HLO text (mirrors __graft_entry__.entry's flagship
    compute)."""
    import jax.numpy as jnp
    import numpy as np

    from ..config import Config
    from ..dataset import BinnedDataset
    from ..models.learner import SerialTreeLearner

    rng = np.random.RandomState(0)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] * 2.0 + X[:, 1] - X[:, 2]
         + 0.5 * rng.normal(size=n) > 0).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "min_data_in_leaf": 5, "verbosity": -1,
                  **(params or {})})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    learner = SerialTreeLearner(ds, cfg)
    grad = jnp.asarray((0.5 - y).astype(np.float32))
    hess = jnp.full((len(y),), 0.25, dtype=jnp.float32)
    fmask = jnp.ones((learner.F,), dtype=bool)
    import jax
    lowered = jax.jit(learner._build_impl).lower(
        learner._part0, grad, hess, jnp.int32(len(y)), fmask)
    return lowered.compile().as_text(), learner


def report(params: Dict[str, Any] = None) -> Dict[str, Any]:
    hlo, learner = compile_tree_build(params)
    out = body_counts(hlo)
    out["params"] = dict(params or {})
    out["mega"] = learner._use_mega
    out["frontier_k"] = learner.frontier_k
    # the hist-state buffer shape (the subtraction path's per-split
    # dynamic-slice target) — its copies are the round-4 smoking gun.
    # The frontier-batched body sizes the state by its speculative slack
    # (L + K slots) instead of L + 1.
    slots = learner.L + max(learner.frontier_k, 1)
    G, B = learner.G, learner.B
    state_shapes = [f"f32[{slots},{G},{B},2]",
                    f"f32[{slots},8,{learner._flat_geom[2]}]"
                    if learner._flat_geom else None]
    out["hist_state_copies"] = sum(
        cnt for shape, cnt in out["copies_by_shape"].items()
        if shape in [s for s in state_shapes if s])
    # whether the state SHAPE appears at all in the body (the mega
    # kernel's invariant is stronger than zero copies: no buffer)
    body_lines = _computation_blocks(hlo)[out["body"]]
    tokens = [s for s in state_shapes if s]
    out["hist_state_shape_lines"] = sum(
        1 for ln in body_lines if any(t in ln for t in tokens))
    return out
