"""jaxlint: static analysis + compile-artifact guards for the TPU
training/serving stack.

Two tiers (driven by ``tools/jaxlint.py`` and tier-1's
``tests/test_jaxlint.py``):

* **Tier A** (:mod:`.astlint`) — AST lint with JAX-specific rules
  JL001–JL005 (host syncs in hot paths, retrace hazards, f64 leaks,
  Python-sized while carries, rank-divergent collectives).
* **Tier B** (:mod:`.artifacts`, :mod:`.hlo`) — designated entry
  points lowered to jaxpr/HLO with structural invariants asserted as
  budgets: while-body copy counts, serving transfer/compile counts,
  fused-step buffer donation, SHAP kernel structure.

Findings and budgets ratchet against the committed
``jaxlint_baseline.json`` (:mod:`.baseline`): pre-existing debt is
pinned, new debt fails tier-1, and paying debt down requires shrinking
the baseline.
"""

from . import astlint, baseline  # noqa: F401
from .astlint import Finding, RULES, finding_counts, lint_source, lint_tree  # noqa: F401
from .baseline import Problem, compare_tier_a, compare_tier_b  # noqa: F401
