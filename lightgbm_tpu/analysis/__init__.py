"""jaxlint: static analysis + compile-artifact guards for the TPU
training/serving stack.

Three tiers (driven by ``tools/jaxlint.py`` and tier-1's
``tests/test_jaxlint.py`` / ``tests/test_conlint.py``):

* **Tier A** (:mod:`.astlint`) — AST lint with JAX-specific rules
  JL001–JL005 (host syncs in hot paths, retrace hazards, f64 leaks,
  Python-sized while carries, rank-divergent collectives).
* **Tier B** (:mod:`.artifacts`, :mod:`.hlo`) — designated entry
  points lowered to jaxpr/HLO with structural invariants asserted as
  budgets: while-body copy counts, serving transfer/compile counts,
  fused-step buffer donation, SHAP kernel structure.
* **Tier C** (:mod:`.conlint`, :mod:`.schedule`) — concurrency
  discipline for the threaded planes: lock-field inference + rules
  CL001–CL004 (unguarded shared writes, lock-order inversions,
  blocking calls under a lock, predicate-free condition waits), plus a
  seeded deterministic schedule explorer that replays the serving
  plane under permuted interleavings at the yield points the static
  pass discovered.

Findings and budgets ratchet against the committed
``jaxlint_baseline.json`` (:mod:`.baseline`): pre-existing debt is
pinned, new debt fails tier-1, and paying debt down requires shrinking
the baseline.
"""

from . import astlint, baseline, conlint  # noqa: F401
from .astlint import Finding, RULES, finding_counts, lint_source, lint_tree  # noqa: F401
from .baseline import Problem, compare_tier_a, compare_tier_b, compare_tier_c  # noqa: F401
