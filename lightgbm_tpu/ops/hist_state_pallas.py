"""Pallas TPU kernel for the per-leaf histogram-state read-modify-write.

The tree loop keeps one (L+1)-slot histogram state and, per split, reads
the parent slot, subtracts the freshly built smaller-child histogram,
and writes both children back (the reference's histogram-subtraction
trick, src/treelearner/serial_tree_learner.cpp ConstructHistograms /
FeatureHistogram::Subtract).  Expressed as XLA dynamic-slice +
dynamic-update-slice on a (L+1, G, B, 2) state inside the tree while
loop, the compiler's memory-space assignment materializes TWO full
f32[L+1, G, B, 2] copies per split (contextual alternate-memory
prefetch around the dynamic slice — PERF.md round-4 "fixed-cost smoking
gun", ~7 ms/iter at 255 leaves).  This kernel performs the same
read+subtract+write as explicit one-row DMAs on a lane-flattened state,
with the state aliased in place, so the per-split cost is ~115 KB of
HBM traffic instead of two ~14.6 MB buffer copies.

State layout: (L+1, 8, WL) f32, each slot the row-major flattening of
the (2, Gp, Bp) histogram — [0] all grad rows, [1] all hess rows, padded
so a slot is exactly (8, WL) with WL a lane multiple (128).  Producers
(ops/histogram.py leaf_hist_slice(layout="flat")) emit this form
directly; the only consumer on the fast path is the Pallas split-search
kernel, which reads (G, BF) grad/hess planes — contiguous sub-blocks of
this layout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def flat_geometry(num_groups: int, num_bins: int):
    """(Gp, Bp, WL) for the flat state: Bp = 16-digit-padded bin axis
    (matches the histogram producer's BH*16), Gp padded so one slot
    flattens to (8, WL) with WL % 128 == 0."""
    Bp = ((num_bins + 15) // 16) * 16
    Bp = max(Bp, 128)
    Gp = num_groups
    while (2 * Gp * Bp) % 1024:
        Gp += 1
    WL = (2 * Gp * Bp) // 8
    return Gp, Bp, WL


@functools.partial(jax.jit, static_argnames=("interpret",))
def hist_rmw_pallas(hist_state, hist_small, idx, *, interpret: bool = False):
    """In-place child-histogram update of the flat state.

    Args:
      hist_state: (L+1, 8, WL) f32, aliased to output 0.
      hist_small: (8, WL) f32 — the smaller child's histogram slot.
      idx: (4,) i32 — [parent_slot, write_a, write_b, small_is_left].

    Returns (state', left, right): state' aliased in place; left/right
    are (8, WL) VMEM copies of the two children for the split search.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L1, S, WL = hist_state.shape
    assert S == 8 and WL % 128 == 0

    def kernel(idx_ref, state_in, small_ref, state_out, left_ref,
               right_ref, parent_buf, sems):
        bl = idx_ref[0]
        wa = idx_ref[1]
        wb = idx_ref[2]
        sil = idx_ref[3]
        rd = pltpu.make_async_copy(state_in.at[bl], parent_buf,
                                   sems.at[0])
        rd.start()
        rd.wait()
        small = small_ref[:]
        large = parent_buf[:] - small
        left_ref[:] = jnp.where(sil == 1, small, large)
        right_ref[:] = jnp.where(sil == 1, large, small)
        # children write-back; serialized — the trash-slot iteration has
        # wa == wb and two in-flight DMAs to one destination would race
        ca = pltpu.make_async_copy(left_ref, state_out.at[wa], sems.at[1])
        ca.start()
        ca.wait()
        cb = pltpu.make_async_copy(right_ref, state_out.at[wb], sems.at[1])
        cb.start()
        cb.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        scratch_shapes=[
            pltpu.VMEM((S, WL), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((L1, S, WL), jnp.float32),
            jax.ShapeDtypeStruct((S, WL), jnp.float32),
            jax.ShapeDtypeStruct((S, WL), jnp.float32),
        ],
        grid_spec=grid_spec,
        input_output_aliases={1: 0},
        interpret=interpret,
    )(idx.astype(jnp.int32), hist_state, hist_small)
