"""Feature binning (host side, NumPy).

TPU-native re-implementation of the reference BinMapper
(src/io/bin.cpp:78-505, include/LightGBM/bin.h:84-259): density-aware greedy
equal-count binning from sampled values, zero-as-a-bin handling, missing-value
handling (None/Zero/NaN), and most-frequent-first categorical bins.

Binning runs once on the host at Dataset construction; the result is a packed
integer bin matrix that lives in TPU HBM for the whole training run.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import log

# reference: include/LightGBM/meta.h:50-56
K_ZERO_THRESHOLD = 1e-35
K_EPSILON = 1e-15
K_SPARSE_THRESHOLD = 0.8  # reference: include/LightGBM/bin.h kSparseThreshold

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1


def _next_after_up(a: float) -> float:
    return math.nextafter(a, math.inf)


def _double_equal_ordered(a: float, b: float) -> bool:
    return b <= _next_after_up(a)


def greedy_find_bin(distinct_values: Sequence[float], counts: Sequence[int],
                    max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy equal-count bin boundary search (reference: bin.cpp GreedyFindBin:78)."""
    num_distinct = len(distinct_values)
    bin_upper: List[float] = []
    assert max_bin > 0
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += counts[i]
            if cur_cnt >= min_data_in_bin:
                val = _next_after_up((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bin_upper or not _double_equal_ordered(bin_upper[-1], val):
                    bin_upper.append(val)
                    cur_cnt = 0
        bin_upper.append(math.inf)
        return bin_upper
    # more distinct values than bins: density-aware greedy packing
    if min_data_in_bin > 0:
        max_bin = min(max_bin, total_cnt // min_data_in_bin)
        max_bin = max(max_bin, 1)
    mean_bin_size = total_cnt / max_bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big = [c >= mean_bin_size for c in counts]
    for i in range(num_distinct):
        if is_big[i]:
            rest_bin_cnt -= 1
            rest_sample_cnt -= counts[i]
    mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0 else math.inf
    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = distinct_values[0]
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= counts[i]
        cur_cnt += counts[i]
        if (is_big[i] or cur_cnt >= mean_bin_size or
                (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = distinct_values[i]
            bin_cnt += 1
            lower_bounds[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0 else math.inf
    bin_cnt += 1
    bin_upper = []
    for i in range(bin_cnt - 1):
        val = _next_after_up((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper or not _double_equal_ordered(bin_upper[-1], val):
            bin_upper.append(val)
    bin_upper.append(math.inf)
    return bin_upper


def find_bin_with_predefined_bin(distinct_values: Sequence[float],
                                 counts: Sequence[int], max_bin: int,
                                 total_sample_cnt: int, min_data_in_bin: int,
                                 forced_upper_bounds: Sequence[float]
                                 ) -> List[float]:
    """Bin boundaries honoring forced upper bounds
    (reference: bin.cpp FindBinWithPredefinedBin:157): the zero bounds and
    the forced bounds are inserted first, then the remaining bin budget is
    distributed across the resulting segments proportionally to their
    sample counts and filled with the greedy search."""
    n = len(distinct_values)
    bin_upper: List[float] = []
    left_cnt = n
    for i in range(n):
        if distinct_values[i] > -K_ZERO_THRESHOLD:
            left_cnt = i
            break
    right_start = -1
    for i in range(left_cnt, n):
        if distinct_values[i] > K_ZERO_THRESHOLD:
            right_start = i
            break
    if max_bin == 2:
        bin_upper.append(K_ZERO_THRESHOLD if left_cnt == 0
                         else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bin_upper.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bin_upper.append(K_ZERO_THRESHOLD)
    bin_upper.append(math.inf)

    max_to_insert = max_bin - len(bin_upper)
    inserted = 0
    for b in forced_upper_bounds:
        if inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bin_upper.append(float(b))
            inserted += 1
    bin_upper.sort()

    free_bins = max_bin - len(bin_upper)
    bounds_to_add: List[float] = []
    value_ind = 0
    nb = len(bin_upper)
    for i in range(nb):
        cnt_in_bin = 0
        distinct_cnt = 0
        bin_start = value_ind
        while value_ind < n and distinct_values[value_ind] < bin_upper[i]:
            cnt_in_bin += counts[value_ind]
            distinct_cnt += 1
            value_ind += 1
        bins_remaining = max_bin - nb - len(bounds_to_add)
        num_sub = int(round(cnt_in_bin * free_bins
                            / max(total_sample_cnt, 1)))
        num_sub = min(num_sub, bins_remaining) + 1
        if i == nb - 1:
            num_sub = bins_remaining + 1
        if distinct_cnt > 0 and num_sub > 0:
            seg = greedy_find_bin(
                distinct_values[bin_start:bin_start + distinct_cnt],
                counts[bin_start:bin_start + distinct_cnt],
                num_sub, cnt_in_bin, min_data_in_bin)
            bounds_to_add.extend(seg[:-1])      # last bound is infinity
    bin_upper.extend(bounds_to_add)
    bin_upper.sort()
    assert len(bin_upper) <= max_bin
    return bin_upper


def find_bin_with_zero_as_one_bin(distinct_values: Sequence[float], counts: Sequence[int],
                                  max_bin: int, total_sample_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Bins with a dedicated zero bin (reference: bin.cpp FindBinWithZeroAsOneBin:242)."""
    num_distinct = len(distinct_values)
    left_cnt_data = 0
    cnt_zero = 0
    right_cnt_data = 0
    for i in range(num_distinct):
        if distinct_values[i] <= -K_ZERO_THRESHOLD:
            left_cnt_data += counts[i]
        elif distinct_values[i] > K_ZERO_THRESHOLD:
            right_cnt_data += counts[i]
        else:
            cnt_zero += counts[i]
    left_cnt = -1
    for i in range(num_distinct):
        if distinct_values[i] > -K_ZERO_THRESHOLD:
            left_cnt = i
            break
    if left_cnt < 0:
        left_cnt = num_distinct

    bin_upper: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        left_max_bin = int(left_cnt_data / max(total_sample_cnt - cnt_zero, 1) * (max_bin - 1))
        left_max_bin = max(1, left_max_bin)
        bin_upper = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                    left_max_bin, left_cnt_data, min_data_in_bin)
        if bin_upper:
            bin_upper[-1] = -K_ZERO_THRESHOLD

    right_start = -1
    for i in range(left_cnt, num_distinct):
        if distinct_values[i] > K_ZERO_THRESHOLD:
            right_start = i
            break
    right_max_bin = max_bin - 1 - len(bin_upper)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(distinct_values[right_start:], counts[right_start:],
                                       right_max_bin, right_cnt_data, min_data_in_bin)
        bin_upper.append(K_ZERO_THRESHOLD)
        bin_upper.extend(right_bounds)
    else:
        bin_upper.append(math.inf)
    assert len(bin_upper) <= max_bin
    return bin_upper


class BinMapper:
    """Maps one feature's raw values to integer bins (reference: bin.h:84)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: int = BIN_NUMERICAL
        self.bin_upper_bound: List[float] = []
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0
        self.most_freq_bin: int = 0

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 0,
                 pre_filter: bool = False, bin_type: int = BIN_NUMERICAL,
                 use_missing: bool = True, zero_as_missing: bool = False,
                 forced_upper_bounds: Optional[List[float]] = None) -> None:
        """Construct the bin mapping from sampled values (reference: bin.cpp:311).

        ``values`` are the sampled non-trivial values; zeros are implied by
        ``total_sample_cnt - len(values)`` like the reference's sparse sampling.
        """
        values = np.asarray(values, dtype=np.float64)
        na_cnt = int(np.isnan(values).sum())
        values = values[~np.isnan(values)]
        num_sample_values = len(values) + na_cnt
        non_na_cnt = len(values)
        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            if na_cnt == 0:
                self.missing_type = MISSING_NONE
                na_cnt = 0
            else:
                self.missing_type = MISSING_NAN

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - non_na_cnt - na_cnt)
        # distinct values, with zero placed at its sorted position
        order = np.argsort(values, kind="stable")
        values = values[order]
        distinct_values: List[float] = []
        counts: List[int] = []
        if non_na_cnt == 0 or (values[0] > 0.0 and zero_cnt > 0):
            distinct_values.append(0.0)
            counts.append(zero_cnt)
        if non_na_cnt > 0:
            distinct_values.append(float(values[0]))
            counts.append(1)
        for i in range(1, non_na_cnt):
            prev, cur = float(values[i - 1]), float(values[i])
            if not _double_equal_ordered(prev, cur):
                if prev < 0.0 and cur > 0.0:
                    distinct_values.append(0.0)
                    counts.append(zero_cnt)
                distinct_values.append(cur)
                counts.append(1)
            else:
                distinct_values[-1] = cur  # use the larger value
                counts[-1] += 1
        if non_na_cnt > 0 and values[-1] < 0.0 and zero_cnt > 0:
            distinct_values.append(0.0)
            counts.append(zero_cnt)

        self.min_val = distinct_values[0] if distinct_values else 0.0
        self.max_val = distinct_values[-1] if distinct_values else 0.0
        cnt_in_bin: List[int] = []
        num_distinct = len(distinct_values)

        if bin_type == BIN_NUMERICAL:
            def bounds(mb, total):
                # forced bounds route through the reference's
                # FindBinWithPredefinedBin split (bin.cpp:302-308)
                if forced_upper_bounds:
                    return find_bin_with_predefined_bin(
                        distinct_values, counts, mb, total,
                        min_data_in_bin, forced_upper_bounds)
                return find_bin_with_zero_as_one_bin(
                    distinct_values, counts, mb, total, min_data_in_bin)

            if self.missing_type == MISSING_ZERO:
                self.bin_upper_bound = bounds(max_bin, total_sample_cnt)
                if len(self.bin_upper_bound) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                self.bin_upper_bound = bounds(max_bin, total_sample_cnt)
            else:  # NaN: last bin reserved for NaN
                self.bin_upper_bound = bounds(max_bin - 1,
                                              total_sample_cnt - na_cnt)
                self.bin_upper_bound.append(math.nan)
            self.num_bin = len(self.bin_upper_bound)
            cnt_in_bin = [0] * self.num_bin
            i_bin = 0
            for i in range(num_distinct):
                while (i_bin < self.num_bin - 1 and
                       distinct_values[i] > self.bin_upper_bound[i_bin]):
                    i_bin += 1
                cnt_in_bin[i_bin] += counts[i]
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            assert self.num_bin <= max_bin
        else:
            # categorical: most-frequent-first bins, bin 0 = NaN/other
            distinct_int: List[int] = []
            counts_int: List[int] = []
            for v, c in zip(distinct_values, counts):
                iv = int(v)
                if iv < 0:
                    na_cnt += c
                    log.warning("Met negative value in categorical features, "
                                "will convert it to NaN")
                elif distinct_int and iv == distinct_int[-1]:
                    counts_int[-1] += c
                else:
                    distinct_int.append(iv)
                    counts_int.append(c)
            rest_cnt = total_sample_cnt - na_cnt
            self.num_bin = 1
            if rest_cnt > 0 and distinct_int:
                # sort by count descending (stable, like SortForPair)
                order2 = sorted(range(len(counts_int)),
                                key=lambda i: -counts_int[i])
                counts_int = [counts_int[i] for i in order2]
                distinct_int = [distinct_int[i] for i in order2]
                cut_cnt = int(round((total_sample_cnt - na_cnt) * 0.99))
                distinct_cnt = len(distinct_int) + (1 if na_cnt > 0 else 0)
                eff_max_bin = min(distinct_cnt, max_bin)
                self.bin_2_categorical = [-1]
                self.categorical_2_bin = {-1: 0}
                cnt_in_bin = [0]
                used_cnt = 0
                cur = 0
                while cur < len(distinct_int) and (used_cnt < cut_cnt or
                                                   self.num_bin < eff_max_bin):
                    if counts_int[cur] < min_data_in_bin and cur > 1:
                        break
                    self.bin_2_categorical.append(distinct_int[cur])
                    self.categorical_2_bin[distinct_int[cur]] = self.num_bin
                    used_cnt += counts_int[cur]
                    cnt_in_bin.append(counts_int[cur])
                    self.num_bin += 1
                    cur += 1
                if cur == len(distinct_int) and na_cnt == 0:
                    self.missing_type = MISSING_NONE
                else:
                    self.missing_type = MISSING_NAN
                cnt_in_bin[0] = int(total_sample_cnt - used_cnt)

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and min_split_data > 0:
            if self._need_filter(cnt_in_bin, total_sample_cnt, min_split_data):
                self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = self.value_to_bin(0.0)
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            max_sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
            if self.most_freq_bin != self.default_bin and max_sparse_rate < K_SPARSE_THRESHOLD:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
        else:
            self.sparse_rate = 1.0

    def _need_filter(self, cnt_in_bin: List[int], total_cnt: int,
                     filter_cnt: int) -> bool:
        """reference: bin.cpp NeedFilter:36."""
        if self.bin_type == BIN_NUMERICAL:
            sum_left = 0
            for i in range(len(cnt_in_bin) - 1):
                sum_left += cnt_in_bin[i]
                if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                    return False
            return True
        if len(cnt_in_bin) <= 2:
            for i in range(len(cnt_in_bin) - 1):
                sum_left = cnt_in_bin[i]
                if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                    return False
            return True
        return False

    # ------------------------------------------------------------------
    def value_to_bin(self, value: float) -> int:
        """Map one raw value to its bin (reference: bin.h ValueToBin:188)."""
        if self.bin_type == BIN_CATEGORICAL:
            if value is None or (isinstance(value, float) and math.isnan(value)):
                return 0
            return self.categorical_2_bin.get(int(value), 0)
        if value is None or math.isnan(value):
            if self.missing_type == MISSING_NAN:
                return self.num_bin - 1
            value = 0.0
        if (self.missing_type == MISSING_ZERO and
                -K_ZERO_THRESHOLD <= value <= K_ZERO_THRESHOLD):
            return self.default_bin
        # binary search over upper bounds
        lo, hi = 0, len(self.bin_upper_bound) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.bin_upper_bound[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def values_to_bins(self, values: np.ndarray,
                       oov_sentinel: bool = False) -> np.ndarray:
        """Vectorized ValueToBin over a column.

        oov_sentinel: categorical mappers only — map out-of-vocabulary
        categories (and NaN) to the out-of-range bin ``num_bin`` instead
        of bin 0.  Bin 0 is the most-frequent category, so a bin-space
        traversal would send unseen categories wherever THAT category
        goes; the sentinel fails every category-set membership test and
        falls to the right child, matching the reference's raw-value
        CategoricalDecision (tree.h) on unseen data.  Training/validation
        binning keeps the reference's bin-0 mapping."""
        values = np.asarray(values, dtype=np.float64)
        out = np.zeros(values.shape, dtype=np.int32)
        if self.bin_type == BIN_CATEGORICAL:
            miss = np.int32(self.num_bin) if oov_sentinel else np.int32(0)
            if not self.categorical_2_bin:
                return np.full(values.shape, miss, dtype=np.int32)
            cats = np.array(list(self.categorical_2_bin.keys()), dtype=np.int64)
            bins = np.array(list(self.categorical_2_bin.values()), dtype=np.int32)
            iv = np.where(np.isnan(values), -1, values).astype(np.int64)
            sorter = np.argsort(cats)
            pos = np.searchsorted(cats[sorter], iv)
            pos = np.clip(pos, 0, len(cats) - 1)
            hit = cats[sorter[pos]] == iv
            out = np.where(hit, bins[sorter[pos]], miss).astype(np.int32)
            return out
        nan_mask = np.isnan(values)
        vals = np.where(nan_mask, 0.0, values)
        bounds = np.asarray(self.bin_upper_bound, dtype=np.float64)
        n_search = len(bounds)
        if self.missing_type == MISSING_NAN:
            n_search -= 1  # last bound is NaN sentinel
        out = np.searchsorted(bounds[:max(n_search - 1, 0)], vals, side="left").astype(np.int32)
        # searchsorted(side=left) gives first idx with bounds[idx] >= v; LightGBM
        # uses v <= bound, identical for exact matches.
        if self.missing_type == MISSING_NAN:
            out = np.where(nan_mask, self.num_bin - 1, out)
        elif self.missing_type == MISSING_ZERO:
            zero = (vals >= -K_ZERO_THRESHOLD) & (vals <= K_ZERO_THRESHOLD)
            out = np.where(zero | nan_mask, self.default_bin, out)
        elif nan_mask.any():
            zero_bin = self.value_to_bin(0.0)
            out = np.where(nan_mask, zero_bin, out)
        return out

    # ------------------------------------------------------------------
    def bin_to_value(self, bin_idx: int) -> float:
        """Representative threshold value for a bin (used for model export)."""
        if self.bin_type == BIN_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        return self.bin_upper_bound[bin_idx]

    def bin_rep_values(self, width: int | None = None,
                       values: np.ndarray | None = None) -> np.ndarray:
        """Per-bin representative raw value for the linear moment planes
        (linear_tree_mode=leafwise_gain, ops/split.py:
        find_best_split_linear).

        Within one bin the regressor is treated as a constant ``rep[b]``,
        so Σx·g / Σx·h / Σx·x·h over a leaf are exact rep-value scalings
        of the already-accumulated G/H histogram.  When ``values`` (the
        raw training column) is given, ``rep[b]`` is the empirical
        within-bin mean E[x | bin=b] — with unit hessians this makes
        Σrep·h equal Σx·h exactly and leaves only the (second-order)
        within-bin x–g covariance unmodeled.  Without it, the fallback is
        ``bin_upper_bound[b]``, which systematically overestimates x by
        up to one bin width and visibly biases slopes in wide tail bins.
        The special bins carry 0.0 by contract (the search derives both
        scan directions from ONE set of moment prefix sums, which is
        only sound when missing rows contribute zero moment mass):

          * the NaN bin (missing_type == NaN: last bin),
          * the MISSING_ZERO default bin (rows there ARE ~0),
          * non-finite bounds clip to ``max_val`` (the top bin's upper
            bound is +inf).

        ``width`` right-pads with zeros to the caller's BF."""
        n = self.num_bin
        out = np.zeros(max(int(width or 0), n), dtype=np.float32)
        if self.bin_type == BIN_CATEGORICAL or self.is_trivial:
            return out
        ub = np.asarray(self.bin_upper_bound, dtype=np.float64)[:n]
        hi = self.max_val if math.isfinite(self.max_val) else 0.0
        lo = self.min_val if math.isfinite(self.min_val) else 0.0
        ub = np.clip(np.nan_to_num(ub, nan=0.0, posinf=hi, neginf=lo),
                     min(lo, hi), max(lo, hi))
        out[:len(ub)] = ub.astype(np.float32)
        if values is not None and len(values):
            vals = np.asarray(values, dtype=np.float64).ravel()
            finite = np.isfinite(vals)
            if finite.any():
                bins = self.values_to_bins(vals[finite])
                cnt = np.bincount(bins, minlength=n).astype(np.float64)
                tot = np.bincount(bins, weights=vals[finite], minlength=n)
                filled = cnt > 0
                out[:n][filled[:n]] = (tot[:n][filled[:n]]
                                       / cnt[:n][filled[:n]]).astype(
                                           np.float32)
        if self.missing_type == MISSING_NAN:
            out[n - 1] = 0.0
        elif self.missing_type == MISSING_ZERO:
            out[self.default_bin] = 0.0
        return out

    def feature_info(self) -> str:
        """`feature_infos` entry for the model file (reference: gbdt_model_text)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BIN_CATEGORICAL:
            cats = sorted(c for c in self.bin_2_categorical if c >= 0)
            return ":".join(str(c) for c in cats)
        return f"[{self.min_val:g}:{self.max_val:g}]"

    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "bin_upper_bound": list(self.bin_upper_bound),
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        bm = cls()
        bm.num_bin = d["num_bin"]
        bm.missing_type = d["missing_type"]
        bm.is_trivial = d["is_trivial"]
        bm.sparse_rate = d["sparse_rate"]
        bm.bin_type = d["bin_type"]
        bm.bin_upper_bound = list(d["bin_upper_bound"])
        bm.bin_2_categorical = list(d["bin_2_categorical"])
        bm.categorical_2_bin = {c: i for i, c in enumerate(bm.bin_2_categorical)}
        bm.min_val = d["min_val"]
        bm.max_val = d["max_val"]
        bm.default_bin = d["default_bin"]
        bm.most_freq_bin = d["most_freq_bin"]
        return bm
