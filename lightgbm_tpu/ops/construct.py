"""Device-vectorized dataset construction.

The host construction path (dataset.py) was a per-feature Python loop
four times over: F stable argsorts for bin finding, an O(n) Python
distinct-value scan per feature, a per-feature ``values_to_bins`` call
per chunk, and an O(F_sparse x groups x n) pairwise loop for EFB
conflict counting.  On multi-million-row inputs that rivals the (now
optimized) train loop.  Both GPU GBDT systems this repo tracks land
the same move on the accelerator side: XGBoost's GPU pipeline bins and
compresses on-device (Mitchell & Frank, arXiv:1806.11248) and
ThunderGBM builds feature-value layouts on the accelerator to feed its
kernels without a host detour (Wen et al., arXiv:1706.08359).

This module provides the vectorized replacements, each bit-identical
to the host oracle in ops/binning.py / dataset.py (asserted by
tests/test_construct_device.py):

* ``sorted_sample_columns`` — ONE column-wise sort of the whole
  (sample_cnt, F) matrix replaces F per-feature stable argsorts; the
  per-feature zero/NaN filtering becomes searchsorted index arithmetic
  on the sorted columns.
* ``find_bin_sorted`` — BinMapper construction from a pre-sorted
  column: the O(n) Python distinct-value scan becomes a vectorized
  nextafter merge, and the greedy equal-count bin search jumps
  cut-to-cut with searchsorted (O(max_bin log n)) in the no-big-bin
  case instead of walking every distinct value.  Falls back to the
  ops/binning.py reference loops whenever the fast path's
  preconditions do not hold.
* ``BatchedMapper`` — one batched values->bins mapping over ALL
  features: a padded (F, B_max) bin-bounds matrix drives a vectorized
  branchless binary search plus vectorized NaN / zero-as-missing /
  default-bin / categorical resolution.  The same code path runs on
  host (numpy) or on device (jnp).  The host path additionally keys
  most numerical columns through an exact uniform-grid table (one
  gather + ``span`` compares instead of a log2(B) branchy binary
  search per element) and bins zero-dominated columns through a
  nonzero-only shortcut — both gated so every output stays
  bit-identical to ``np.searchsorted``.
* ``conflict_matrix`` — EFB conflict counting as one nonzero-mask
  matmul (F_sparse, n) @ (n, F_sparse) instead of the host pairwise
  loop; with the reference's max_conflict_rate = 0.0 the pairwise
  counts decide the greedy coloring bit-identically to the
  union-mask loop.
* ``DeviceIngest`` — streams packed row chunks straight into the
  learner's transposed (G, N_pad) device layout with double-buffered
  host->device copies, so the full row-major host binned matrix, its
  transpose and the padded copy never materialize.

``construct_device=auto|on|off`` (config.py) selects the path; ``off``
keeps the original per-feature loops as the oracle.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import log
from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, K_SPARSE_THRESHOLD,
                      K_ZERO_THRESHOLD, MISSING_NAN, MISSING_NONE,
                      MISSING_ZERO, BinMapper, find_bin_with_predefined_bin,
                      greedy_find_bin)

# ---------------------------------------------------------------------------
# Shared row geometry (must agree with models/learner.py so a dataset-built
# device buffer can be consumed by the learner without reshaping)
# ---------------------------------------------------------------------------


def _pow2ceil(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


def row_geometry(tpu_row_chunk: int, n: int) -> Tuple[int, int, int]:
    """(row_chunk, row0, n_pad) for ``n`` data rows — the learner's layout:
    [C front-pad rows][N data rows][>= 2C tail-pad rows] (see
    models/learner.py row-geometry comment for why two tail chunks)."""
    c = min(int(tpu_row_chunk), max(_pow2ceil(n), 256))
    if c & (c - 1):
        c = _pow2ceil(c)
    c = min(c, 1 << 15)
    n_pad = c + ((n + c - 1) // c + 2) * c
    return c, c, n_pad


def resolve_mode(config, is_reference: bool, is_distributed: bool
                 ) -> Tuple[bool, bool, bool]:
    """(vectorized, device_ingest, keep_host_binned) for this dataset.

    * ``off``  — the original per-feature host loops (the oracle).
    * ``auto`` — vectorized host construction everywhere; training
      datasets additionally stream into the device (G, N_pad) buffer
      (the learner consumes it), host binned is still materialized.
    * ``on``   — like auto, but the host binned matrix is NOT
      materialized for training datasets (it can be recovered from the
      device buffer on demand).
    Validation datasets (``is_reference``) and multi-process
    construction never device-ingest: their consumers want row-major
    host bins / rank-local shards.
    """
    mode = str(getattr(config, "construct_device", "auto") or "auto").lower()
    if mode not in ("auto", "on", "off"):
        log.warning("construct_device=%s unknown; using 'auto'", mode)
        mode = "auto"
    if mode == "off":
        return False, False, True
    ingest_ok = not is_reference and not is_distributed
    if mode == "on" and not ingest_ok:
        log.warning("construct_device=on ignored for %s construction; "
                    "using the vectorized host path",
                    "aligned (validation)" if is_reference
                    else "multi-process")
    if mode == "on" and ingest_ok:
        return True, True, False
    return True, ingest_ok, True


# ---------------------------------------------------------------------------
# Vectorized bin finding (stage 1: one matrix sort + index arithmetic)
# ---------------------------------------------------------------------------


def sorted_sample_columns(sample: np.ndarray, workers: int = 1
                          ) -> Dict[str, np.ndarray]:
    """ONE column-wise sort of the whole (sample_cnt, F) matrix plus the
    per-feature zero/NaN boundaries, replacing F stable argsorts.

    NaNs sort to the end of each column (numpy guarantee), so the
    per-feature "non-zero + NaN sample" the mappers consume is just two
    index ranges of the sorted column.  ``workers`` > 1 sorts column
    blocks on threads (np.sort releases the GIL; per-column results are
    unaffected by the split).
    """
    sample = np.asarray(sample, dtype=np.float64)
    ncol = sample.shape[1]
    if workers > 1 and ncol > 1:
        from concurrent.futures import ThreadPoolExecutor
        svals = np.empty_like(sample)
        blocks = [slice(b, min(b + (ncol + workers - 1) // workers,
                               ncol))
                  for b in range(0, ncol,
                                 (ncol + workers - 1) // workers)]
        with ThreadPoolExecutor(max_workers=workers) as ex:
            list(ex.map(
                lambda blk: svals.__setitem__(
                    (slice(None), blk), np.sort(sample[:, blk], axis=0)),
                blocks))
    else:
        svals = np.sort(sample, axis=0)              # one sort, all columns
    nan_cnt = np.count_nonzero(np.isnan(sample), axis=0)
    m = sample.shape[0] - nan_cnt                    # non-NaN length per col
    # abs(v) > K_ZERO_THRESHOLD keeps v < -K or v > K; on the sorted
    # column those are [0, lo) and [hi, m)
    lo = np.empty(sample.shape[1], dtype=np.int64)
    hi = np.empty(sample.shape[1], dtype=np.int64)
    for f in range(sample.shape[1]):
        col = svals[: m[f], f]
        lo[f] = np.searchsorted(col, -K_ZERO_THRESHOLD, side="left")
        hi[f] = np.searchsorted(col, K_ZERO_THRESHOLD, side="right")
    return {"sorted": svals, "nan_cnt": nan_cnt, "non_nan": m,
            "lo": lo, "hi": hi}


def _distinct_from_sorted(vals: np.ndarray, zero_cnt: int,
                          counts: Optional[np.ndarray] = None
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Distinct values + counts from an ascending non-zero non-NaN value
    array, with the implied-zero bin spliced in — the vectorized replica
    of the O(n) Python scan in BinMapper.find_bin (ops/binning.py:270).

    Merge rule (reference bin.cpp): adjacent values with
    ``b <= nextafter(a, inf)`` collapse into one distinct value keeping
    the LARGER value; a run's representative is therefore its last
    element.

    ``counts`` (optional) marks ``vals`` as an already-deduplicated
    weighted array — each entry stands for ``counts[i]`` raw
    occurrences (the sketch path, ops/sketch.py).  Identical raw values
    always share one weighted entry, so run boundaries — and therefore
    the merged distincts — match the unweighted scan bit for bit.
    """
    m = len(vals)
    if m == 0:
        return (np.asarray([0.0]), np.asarray([zero_cnt], dtype=np.int64))
    if m == 1:
        d = np.asarray([float(vals[0])])
        c = (np.asarray([1], dtype=np.int64) if counts is None
             else np.asarray([int(counts[0])], dtype=np.int64))
    else:
        merge = vals[1:] <= np.nextafter(vals[:-1], np.inf)
        ends = np.flatnonzero(np.concatenate([~merge, [True]]))
        d = vals[ends]
        starts = np.concatenate([[0], ends[:-1] + 1])
        if counts is None:
            c = (ends - starts + 1).astype(np.int64)
        else:
            csum = np.concatenate([[0], np.cumsum(counts)])
            c = (csum[ends + 1] - csum[starts]).astype(np.int64)
    # zero insertion, replicating find_bin's three sites exactly:
    #  * all-positive sample with zeros present -> leading zero bin
    #  * sign change between adjacent distincts -> zero spliced between
    #    (with zero_cnt, EVEN when zero_cnt == 0, like the reference)
    #  * all-negative sample with zeros present -> trailing zero bin
    if d[0] > 0.0:
        if zero_cnt > 0:
            d = np.concatenate([[0.0], d])
            c = np.concatenate([[zero_cnt], c])
    elif d[-1] < 0.0:
        if zero_cnt > 0:
            d = np.concatenate([d, [0.0]])
            c = np.concatenate([c, [zero_cnt]])
    else:
        pos = int(np.searchsorted(d, 0.0, side="left"))
        if 0 < pos < len(d) and d[pos - 1] < 0.0 and d[pos] > 0.0:
            d = np.concatenate([d[:pos], [0.0], d[pos:]])
            c = np.concatenate([c[:pos], [zero_cnt], c[pos:]])
    return d, c


def _double_equal_ordered(a: float, b: float) -> bool:
    return b <= math.nextafter(a, math.inf)


def _greedy_find_bin_fast(distinct: np.ndarray, counts: np.ndarray,
                          max_bin: int, total_cnt: int,
                          min_data_in_bin: int) -> List[float]:
    """greedy_find_bin (ops/binning.py:42) with the dominant case — more
    distinct values than bins, no 'big' bins — jumped cut-to-cut via
    searchsorted on the count cumsum: O(max_bin log n) instead of an
    O(n) Python walk.  Any other case delegates to the reference loop
    (bit-identity is trivially preserved there)."""
    num_distinct = len(distinct)
    assert max_bin > 0
    if num_distinct <= max_bin:
        # <= max_bin Python iterations: already cheap, reuse the oracle
        return greedy_find_bin(distinct, counts, max_bin, total_cnt,
                               min_data_in_bin)
    if min_data_in_bin > 0:
        max_bin = max(min(max_bin, total_cnt // min_data_in_bin), 1)
    mean_bin_size = total_cnt / max_bin
    # max() compares ONE scalar (np.any(counts >= float) would promote
    # the whole int64 array to f64 first)
    if len(counts) and int(counts.max()) >= mean_bin_size:
        # 'big' distinct values re-plan the running mean mid-walk in a
        # data-dependent way — take the reference loop
        return greedy_find_bin(distinct, counts, max_bin, total_cnt,
                               min_data_in_bin)
    # No big bins: every close happens at the first index i (searched,
    # not walked) where the count accumulated since the last cut
    # reaches the CURRENT mean; after each close the mean is re-derived
    # from the remaining samples and bins, exactly like the loop.
    # f64 cumsum: the cut search needle (base + mean_bin_size) is a
    # float, and searchsorted over an int64 array with a float needle
    # silently promotes THE WHOLE ARRAY to f64 on every call.  Counts
    # are exact in f64 (<= 2^53), so the comparisons are identical.
    cum = np.cumsum(counts, dtype=np.float64)
    upper_bounds: List[float] = []
    lower_bounds: List[float] = [float(distinct[0])]
    bin_cnt = 0
    rest_bin_cnt = max_bin
    base = 0                         # samples consumed before current bin
    start = 0                        # first distinct index of current bin
    while bin_cnt < max_bin - 1 and start <= num_distinct - 2:
        # first i >= start with cum[i] - base >= mean_bin_size; the loop
        # only closes at i <= num_distinct - 2
        i = int(np.searchsorted(cum[: num_distinct - 1],
                                base + mean_bin_size, side="left"))
        if i >= num_distinct - 1:
            break                    # never reaches the mean: loop ends
        if i < start:
            i = start
        upper_bounds.append(float(distinct[i]))
        lower_bounds.append(float(distinct[i + 1]))
        bin_cnt += 1
        if bin_cnt >= max_bin - 1:
            break
        rest_bin_cnt -= 1
        rest_sample_cnt = total_cnt - int(cum[i])
        mean_bin_size = (rest_sample_cnt / rest_bin_cnt
                         if rest_bin_cnt > 0 else math.inf)
        base = int(cum[i])
        start = i + 1
    bin_cnt += 1
    bin_upper: List[float] = []
    for i in range(bin_cnt - 1):
        val = math.nextafter((upper_bounds[i] + lower_bounds[i + 1]) / 2.0,
                             math.inf)
        if not bin_upper or not _double_equal_ordered(bin_upper[-1], val):
            bin_upper.append(val)
    bin_upper.append(math.inf)
    return bin_upper


def _find_bin_with_zero_as_one_bin_fast(distinct: np.ndarray,
                                        counts: np.ndarray, max_bin: int,
                                        total_sample_cnt: int,
                                        min_data_in_bin: int) -> List[float]:
    """find_bin_with_zero_as_one_bin (ops/binning.py:174) with the
    left/zero/right partition computed by searchsorted on the (sorted)
    distinct array instead of Python scans."""
    n = len(distinct)
    left_cnt = int(np.searchsorted(distinct, -K_ZERO_THRESHOLD,
                                   side="right"))
    right_start = int(np.searchsorted(distinct, K_ZERO_THRESHOLD,
                                      side="right"))
    left_cnt_data = int(counts[:left_cnt].sum())
    right_cnt_data = int(counts[right_start:].sum())
    # the reference counts zeros from the distinct list; replicate that
    # (the two agree except for NaN counts, which never reach here)
    cnt_zero = int(counts[left_cnt:right_start].sum())

    bin_upper: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        left_max_bin = int(left_cnt_data
                           / max(total_sample_cnt - cnt_zero, 1)
                           * (max_bin - 1))
        left_max_bin = max(1, left_max_bin)
        bin_upper = _greedy_find_bin_fast(
            distinct[:left_cnt], counts[:left_cnt], left_max_bin,
            left_cnt_data, min_data_in_bin)
        if bin_upper:
            bin_upper[-1] = -K_ZERO_THRESHOLD
    rs = right_start if right_start < n else -1
    right_max_bin = max_bin - 1 - len(bin_upper)
    if rs >= 0 and right_max_bin > 0:
        right_bounds = _greedy_find_bin_fast(
            distinct[rs:], counts[rs:], right_max_bin, right_cnt_data,
            min_data_in_bin)
        bin_upper.append(K_ZERO_THRESHOLD)
        bin_upper.extend(right_bounds)
    else:
        bin_upper.append(math.inf)
    assert len(bin_upper) <= max_bin
    return bin_upper


def find_bin_sorted(sorted_nonzero: np.ndarray, na_cnt: int,
                    total_sample_cnt: int, max_bin: int,
                    min_data_in_bin: int = 3, min_split_data: int = 0,
                    pre_filter: bool = False, bin_type: int = BIN_NUMERICAL,
                    use_missing: bool = True, zero_as_missing: bool = False,
                    forced_upper_bounds: Optional[List[float]] = None
                    ) -> BinMapper:
    """BinMapper.find_bin (ops/binning.py:241) from an ALREADY-SORTED
    non-zero non-NaN value array — the per-feature stage of the batched
    construction.  Distinct extraction, bin counting and the greedy
    search are vectorized; every branch mirrors the oracle exactly."""
    vals = np.asarray(sorted_nonzero, dtype=np.float64)
    non_na_cnt = len(vals)
    zero_cnt = int(total_sample_cnt - non_na_cnt - na_cnt)
    distinct, counts = _distinct_from_sorted(vals, zero_cnt)
    if non_na_cnt == 0 and zero_cnt == 0:
        # find_bin still emits the zero distinct with its (zero) count
        distinct, counts = np.asarray([0.0]), np.asarray([0],
                                                         dtype=np.int64)
    return mapper_from_distinct(
        distinct, counts, na_cnt, total_sample_cnt, max_bin,
        min_data_in_bin=min_data_in_bin, min_split_data=min_split_data,
        pre_filter=pre_filter, bin_type=bin_type, use_missing=use_missing,
        zero_as_missing=zero_as_missing,
        forced_upper_bounds=forced_upper_bounds)


def mapper_from_distinct(distinct: np.ndarray, counts: np.ndarray,
                         na_cnt: int, total_sample_cnt: int, max_bin: int,
                         min_data_in_bin: int = 3, min_split_data: int = 0,
                         pre_filter: bool = False,
                         bin_type: int = BIN_NUMERICAL,
                         use_missing: bool = True,
                         zero_as_missing: bool = False,
                         forced_upper_bounds: Optional[List[float]] = None
                         ) -> BinMapper:
    """The shared distinct+counts -> BinMapper tail of the bin finder:
    bounds search, per-bin counting, the categorical most-frequent-first
    walk, pre-filtering and the default/most-freq-bin epilogue.  Both
    the exact path (``find_bin_sorted``, distincts from a full column
    sort) and the out-of-core sketch path (ops/sketch.py, distincts
    from merged cell maxes) end here, which is what makes the two
    bit-comparable."""
    bm = BinMapper()
    if not use_missing:
        bm.missing_type = MISSING_NONE
    elif zero_as_missing:
        bm.missing_type = MISSING_ZERO
    else:
        bm.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE
    bm.bin_type = bin_type
    bm.default_bin = 0
    distinct = np.asarray(distinct, dtype=np.float64)
    counts = np.asarray(counts, dtype=np.int64)
    bm.min_val = float(distinct[0]) if len(distinct) else 0.0
    bm.max_val = float(distinct[-1]) if len(distinct) else 0.0
    num_distinct = len(distinct)

    if bin_type == BIN_NUMERICAL:
        def bounds(mb, total):
            if forced_upper_bounds:
                return find_bin_with_predefined_bin(
                    list(distinct), list(counts), mb, total,
                    min_data_in_bin, forced_upper_bounds)
            return _find_bin_with_zero_as_one_bin_fast(
                distinct, counts, mb, total, min_data_in_bin)

        if bm.missing_type == MISSING_ZERO:
            bm.bin_upper_bound = bounds(max_bin, total_sample_cnt)
            if len(bm.bin_upper_bound) == 2:
                bm.missing_type = MISSING_NONE
        elif bm.missing_type == MISSING_NONE:
            bm.bin_upper_bound = bounds(max_bin, total_sample_cnt)
        else:
            bm.bin_upper_bound = bounds(max_bin - 1,
                                        total_sample_cnt - na_cnt)
            bm.bin_upper_bound.append(math.nan)
        bm.num_bin = len(bm.bin_upper_bound)
        # vectorized cnt_in_bin: first bin whose upper >= value, capped
        # at num_bin-1 — identical to the oracle's walking i_bin
        search = np.asarray(bm.bin_upper_bound[: bm.num_bin - 1],
                            dtype=np.float64)
        idx = np.searchsorted(search, distinct, side="left")
        cnt_in_bin = np.bincount(idx, weights=counts,
                                 minlength=bm.num_bin).astype(np.int64)
        if bm.missing_type == MISSING_NAN:
            cnt_in_bin[bm.num_bin - 1] = na_cnt
        assert bm.num_bin <= max_bin
        cnt_in_bin = list(cnt_in_bin)
    else:
        # categorical: truncate toward zero like int(); negatives fold
        # into the NaN bin with the reference's per-value warning
        ivs = distinct.astype(np.int64)
        neg = ivs < 0
        if bool(neg.any()):
            na_cnt += int(counts[neg].sum())
            for _ in range(int(neg.sum())):
                log.warning("Met negative value in categorical features, "
                            "will convert it to NaN")
        ivs, counts_i = ivs[~neg], counts[~neg].astype(np.int64)
        if len(ivs):
            # ascending distinct floats can collapse after truncation
            ends = np.flatnonzero(np.concatenate(
                [ivs[1:] != ivs[:-1], [True]]))
            starts = np.concatenate([[0], ends[:-1] + 1])
            csum = np.concatenate([[0], np.cumsum(counts_i)])
            distinct_int = ivs[ends]
            counts_int = (csum[ends + 1] - csum[starts]).astype(np.int64)
        else:
            distinct_int = np.asarray([], dtype=np.int64)
            counts_int = np.asarray([], dtype=np.int64)
        rest_cnt = total_sample_cnt - na_cnt
        bm.num_bin = 1
        cnt_in_bin = [0]
        if rest_cnt > 0 and len(distinct_int):
            order2 = np.argsort(-counts_int, kind="stable")
            counts_l = counts_int[order2]
            distinct_l = distinct_int[order2]
            cut_cnt = int(round((total_sample_cnt - na_cnt) * 0.99))
            distinct_cnt = len(distinct_l) + (1 if na_cnt > 0 else 0)
            eff_max_bin = min(distinct_cnt, max_bin)
            bm.bin_2_categorical = [-1]
            bm.categorical_2_bin = {-1: 0}
            used_cnt = 0
            cur = 0
            while cur < len(distinct_l) and (used_cnt < cut_cnt or
                                             bm.num_bin < eff_max_bin):
                if counts_l[cur] < min_data_in_bin and cur > 1:
                    break
                bm.bin_2_categorical.append(int(distinct_l[cur]))
                bm.categorical_2_bin[int(distinct_l[cur])] = bm.num_bin
                used_cnt += int(counts_l[cur])
                cnt_in_bin.append(int(counts_l[cur]))
                bm.num_bin += 1
                cur += 1
            if cur == len(distinct_l) and na_cnt == 0:
                bm.missing_type = MISSING_NONE
            else:
                bm.missing_type = MISSING_NAN
            cnt_in_bin[0] = int(total_sample_cnt - used_cnt)

    bm.is_trivial = bm.num_bin <= 1
    if not bm.is_trivial and pre_filter and min_split_data > 0:
        if bm._need_filter(cnt_in_bin, total_sample_cnt, min_split_data):
            bm.is_trivial = True
    if not bm.is_trivial:
        bm.default_bin = bm.value_to_bin(0.0)
        bm.most_freq_bin = int(np.argmax(cnt_in_bin))
        max_sparse_rate = cnt_in_bin[bm.most_freq_bin] / total_sample_cnt
        if (bm.most_freq_bin != bm.default_bin
                and max_sparse_rate < K_SPARSE_THRESHOLD):
            bm.most_freq_bin = bm.default_bin
        bm.sparse_rate = cnt_in_bin[bm.most_freq_bin] / total_sample_cnt
    else:
        bm.sparse_rate = 1.0
    return bm


# ---------------------------------------------------------------------------
# Batched values -> bins mapping (host numpy or device jnp, one code path)
# ---------------------------------------------------------------------------

_CAT_PAD = np.int64(2 ** 62)       # > any real category key
_GRID_NCELL = 8192                 # grid cells per feature (32KB table)
_GRID_MAXSPAN = 4                  # fall back to searchsorted past this


def _searchsorted_rows(bounds, vals, xp):
    """Per-row ``searchsorted(bounds[f], vals[:, f], side='left')`` as a
    branchless batched binary search: ``bounds`` (F, B) row-sorted,
    ``vals`` (n, F); returns (n, F) int32.  Identical semantics in
    numpy and jnp."""
    if xp is np:
        # host: F C-speed searchsorted calls beat the branchless form,
        # whose ~log2(B) iterations each stream several (n, F) f64
        # temporaries through memory.  side='left' == count of bounds
        # strictly below the value == the branchless result.
        out = np.empty(vals.shape, dtype=np.int32)
        for f in range(bounds.shape[0]):
            out[:, f] = np.searchsorted(bounds[f], vals[:, f],
                                        side="left")
        return out
    f_idx = xp.arange(bounds.shape[0])[None, :]
    b = bounds.shape[1]
    lo = xp.zeros(vals.shape, dtype=xp.int32)
    hi = xp.full(vals.shape, b, dtype=xp.int32)
    for _ in range(max(b - 1, 0).bit_length() + 1):
        active = lo < hi               # converged lanes must not move
        mid = (lo + hi) >> 1
        # mid == b only once lo == hi == b (converged); clamp the gather
        below = (bounds[f_idx, xp.minimum(mid, b - 1)] < vals) & active
        lo = xp.where(below, mid + 1, lo)
        hi = xp.where(active & ~below, mid, hi)
    return lo


class BatchedMapper:
    """Padded per-feature tables driving ONE vectorized mapping over all
    used features — the batched replacement for the per-feature
    ``BinMapper.values_to_bins`` loop.  ``map_chunk`` reproduces the
    per-feature results bit-identically (tests/test_construct_device.py)
    and runs through numpy on host or jnp on device."""

    def __init__(self, bin_mappers: Sequence[BinMapper],
                 used_features: Sequence[int]):
        self.used_features = list(used_features)
        F = len(self.used_features)
        self.num_cols = F
        mappers = [bin_mappers[f] for f in self.used_features]
        self.is_cat = np.asarray(
            [bm.bin_type == BIN_CATEGORICAL for bm in mappers], dtype=bool)
        self.missing_type = np.asarray(
            [bm.missing_type for bm in mappers], dtype=np.int32)
        self.num_bin = np.asarray([bm.num_bin for bm in mappers],
                                  dtype=np.int32)
        self.default_bin = np.asarray([bm.default_bin for bm in mappers],
                                      dtype=np.int32)
        # bin of a literal 0.0 value (the NaN target for MISSING_NONE)
        self.zero_bin = np.asarray(
            [0 if self.is_cat[i] else bm.value_to_bin(0.0)
             for i, bm in enumerate(mappers)], dtype=np.int32)
        # numerical search bounds: drop the NaN sentinel, pad with +inf —
        # searchsorted over the padded row equals searchsorted over the
        # oracle's bounds[:n_search-1] for every input (inf catches the
        # overflow at the same index)
        b_max = 1
        for bm in mappers:
            if bm.bin_type == BIN_NUMERICAL:
                n_search = len(bm.bin_upper_bound)
                if bm.missing_type == MISSING_NAN:
                    n_search -= 1
                b_max = max(b_max, max(n_search - 1, 0))
        self.bounds = np.full((F, b_max), np.inf, dtype=np.float64)
        for i, bm in enumerate(mappers):
            if bm.bin_type != BIN_NUMERICAL:
                continue
            n_search = len(bm.bin_upper_bound)
            if bm.missing_type == MISSING_NAN:
                n_search -= 1
            k = max(n_search - 1, 0)
            if k:
                self.bounds[i, :k] = bm.bin_upper_bound[:k]
        # true (unpadded) bound count per feature: the host path
        # searches bounds[f, :blen[f]] — identical results (inf pad
        # entries never compare below a finite value) with log2(blen)
        # probes instead of log2(b_max) for few-bin features
        self._blen = np.asarray(
            [int(np.sum(np.isfinite(self.bounds[i])))
             for i in range(F)], dtype=np.int64)
        # uniform-grid accelerator for the host per-column search: a
        # NCELL-cell grid over [b0, b_last] where cell(v) is monotone
        # in v, so with lo_tab[c] = #bounds in cells < c the exact
        # searchsorted('left') result is lo_tab[cell(v)] plus at most
        # `span` (= max bounds per cell) one-gather correction steps —
        # bounds in earlier cells are always < v, later cells never,
        # the own cell resolves by direct compares.  Features whose
        # bounds cluster past MAXSPAN per cell keep np.searchsorted.
        self._grid: list = [None] * F
        for i in range(F):
            if self.is_cat[i]:
                continue
            blen = int(self._blen[i])
            if blen < 2:
                continue
            b = self.bounds[i, :blen]
            g0, top = b[0], b[-1]
            if not (np.isfinite(g0) and np.isfinite(top)) or top <= g0:
                continue
            inv_w = _GRID_NCELL / (top - g0)
            if not np.isfinite(inv_w):
                continue
            cellb = np.clip((b - g0) * inv_w,
                            0, _GRID_NCELL - 1).astype(np.int32)
            counts = np.bincount(cellb, minlength=_GRID_NCELL)
            span = int(counts.max())
            if span > _GRID_MAXSPAN:
                continue
            lo_tab = np.zeros(_GRID_NCELL, np.int32)
            np.cumsum(counts[:-1], out=lo_tab[1:])
            self._grid[i] = (g0, inv_w, lo_tab,
                             np.append(b, np.inf), span)
        # zero-domination hint from the construction sample: the
        # count_nonzero probe feeding the sparse shortcut below only
        # runs where the sample says zeros might dominate — the gate
        # picks between two exact paths, so a stale hint costs speed,
        # never correctness
        self._try_sparse = np.asarray(
            [(not self.is_cat[i]) and bm.sparse_rate >= 0.4
             and bm.most_freq_bin == self.zero_bin[i]
             for i, bm in enumerate(mappers)], dtype=bool)
        # bins fit a byte when every feature's bin count does: the
        # feature-major host path then emits uint8 rows (4x less
        # write traffic); consumers upcast where they do arithmetic
        self._out_dtype = (np.uint8 if (self.num_bin.size == 0
                                        or int(self.num_bin.max()) <= 255)
                           else np.int32)
        # categorical tables: sorted keys padded with a huge sentinel
        self.has_cat = bool(self.is_cat.any())
        if self.has_cat:
            c_max = max((len(bm.categorical_2_bin) for bm in mappers
                         if bm.bin_type == BIN_CATEGORICAL), default=0)
            c_max = max(c_max, 1)
            self.cat_keys = np.full((F, c_max), _CAT_PAD, dtype=np.int64)
            self.cat_bins = np.zeros((F, c_max), dtype=np.int32)
            for i, bm in enumerate(mappers):
                if bm.bin_type != BIN_CATEGORICAL or not bm.categorical_2_bin:
                    continue
                keys = np.asarray(list(bm.categorical_2_bin.keys()),
                                  dtype=np.int64)
                vals = np.asarray(list(bm.categorical_2_bin.values()),
                                  dtype=np.int32)
                srt = np.argsort(keys)
                self.cat_keys[i, : len(keys)] = keys[srt]
                self.cat_bins[i, : len(keys)] = vals[srt]
        # column index sets for the host fast path (_map_chunk_np):
        # only columns whose missing type can actually fire pay a fixup
        nc = ~self.is_cat
        self._idx_nan = np.flatnonzero(
            (self.missing_type == MISSING_NAN) & nc)
        self._idx_zero = np.flatnonzero(
            (self.missing_type == MISSING_ZERO) & nc)
        self._idx_none = np.flatnonzero(
            (self.missing_type == MISSING_NONE) & nc)
        self._idx_cat = np.flatnonzero(self.is_cat)
        # raw searchsorted result of a literal 0.0 per feature (before
        # any missing fixup) — the shared answer for every exact zero
        # in the sparse-column shortcut below
        self._zero_ss = np.sum(self.bounds < 0.0, axis=1).astype(np.int32)

    def map_chunk_T(self, chunk: np.ndarray,
                    oov_sentinel: bool = False) -> np.ndarray:
        """Host fast path, feature-major: (n, F_used) raw values ->
        (F_used, n) int32 bins, C-order (each feature's bins form one
        contiguous row — writing bins column-wise into a row-major
        (n, F) matrix touches a full cache line per element).

        Per-column C-speed searchsorted with column-gated
        NaN/zero/default fixups — bit-identical to the batched
        where-chain in ``map_chunk``: a where over an all-false mask is
        the identity, so skipping it for columns where the condition
        cannot fire changes nothing."""
        # one feature-major copy up front: every per-column pass below
        # (count_nonzero, searchsorted, fixups) then reads a contiguous
        # ~0.5MB row instead of striding across the whole row-major
        # chunk — measured ~15% off the chunk map even net of the
        # transpose cost (blocked so each transpose tile stays
        # cache-resident)
        src = np.asarray(chunk, dtype=np.float64)
        n = src.shape[0]
        vals = np.empty((self.num_cols, n), dtype=np.float64)
        for s in range(0, n, 4096):
            e = min(s + 4096, n)
            vals[:, s:e] = src[s:e].T
        out = np.empty((self.num_cols, n), dtype=self._out_dtype)
        nan_mask = np.isnan(vals)
        col_nan = nan_mask.any(axis=1)
        # scratch shared by every grid-search column in this chunk
        f8 = np.empty(n)
        i4 = np.empty(n, dtype=np.int32)
        g8 = np.empty(n)
        bl = np.empty(n, dtype=bool)
        for f in range(self.num_cols):
            if self.is_cat[f]:
                continue
            col = vals[f]
            if col_nan[f]:
                col = np.where(nan_mask[f], 0.0, col)
            bounds = self.bounds[f, : self._blen[f]]
            nz_cnt = (int(np.count_nonzero(col))
                      if self._try_sparse[f] else n)
            if nz_cnt * 2 < n:
                # zero-dominated column: binary-search only the
                # non-zeros; every exact 0.0 (incl. -0.0 and the
                # scrubbed NaNs above) shares the precomputed result,
                # so this is bit-identical at a fraction of the
                # searchsorted work
                idx = np.flatnonzero(col)
                row = out[f]
                row.fill(self._zero_ss[f])
                row[idx] = np.searchsorted(bounds, col[idx],
                                           side="left")
            elif self._grid[f] is not None:
                g0, inv_w, lo_tab, bpad, span = self._grid[f]
                np.subtract(col, g0, out=f8)
                np.multiply(f8, inv_w, out=f8)
                np.clip(f8, 0, _GRID_NCELL - 1, out=f8)
                np.copyto(i4, f8, casting="unsafe")
                res = lo_tab[i4]
                for _ in range(span):
                    np.take(bpad, res, out=g8)
                    np.greater(col, g8, out=bl)
                    np.add(res, bl, out=res, casting="unsafe")
                out[f] = res
            else:
                out[f] = np.searchsorted(bounds, col, side="left")
        for f in self._idx_nan:
            if col_nan[f]:
                out[f][nan_mask[f]] = self.num_bin[f] - 1
        for f in self._idx_zero:
            col = vals[f]
            if col_nan[f]:
                col = np.where(nan_mask[f], 0.0, col)
            # NaN -> 0.0 above, so |col| <= K covers the chain's
            # (zeroish | nan_mask) exactly
            z = (col >= -K_ZERO_THRESHOLD) & (col <= K_ZERO_THRESHOLD)
            out[f][z] = self.default_bin[f]
        for f in self._idx_none:
            if col_nan[f]:
                out[f][nan_mask[f]] = self.zero_bin[f]
        for f in self._idx_cat:
            iv = np.where(nan_mask[f], -1.0,
                          vals[f]).astype(np.int64)
            keys = self.cat_keys[f]
            pos = np.minimum(np.searchsorted(keys, iv, side="left"),
                             keys.shape[0] - 1)
            hit = keys[pos] == iv
            miss = np.int32(self.num_bin[f]) if oov_sentinel \
                else np.int32(0)
            out[f] = np.where(hit, self.cat_bins[f][pos], miss)
        return out

    def map_chunk(self, chunk, xp=np, oov_sentinel: bool = False):
        """(n, F_used) raw values -> (n, F_used) int32 bins.  ``chunk``
        columns follow ``used_features`` order.  ``xp`` is numpy or
        jax.numpy; categorical resolution always runs through the same
        vectorized search (int64 keys) on host tables."""
        if xp is np:
            # transposed VIEW of the feature-major result: mat[:, i] is
            # the contiguous row map_chunk_T wrote, so per-feature
            # consumers pay no copy
            return self.map_chunk_T(chunk, oov_sentinel).T
        vals = xp.asarray(chunk)
        nan_mask = xp.isnan(vals)
        safe = xp.where(nan_mask, 0.0, vals)
        out = _searchsorted_rows(xp.asarray(self.bounds), safe, xp)
        mt = xp.asarray(self.missing_type)[None, :]
        nbin = xp.asarray(self.num_bin)[None, :]
        dbin = xp.asarray(self.default_bin)[None, :]
        zbin = xp.asarray(self.zero_bin)[None, :]
        out = xp.where((mt == MISSING_NAN) & nan_mask, nbin - 1, out)
        zeroish = (safe >= -K_ZERO_THRESHOLD) & (safe <= K_ZERO_THRESHOLD)
        out = xp.where((mt == MISSING_ZERO) & (zeroish | nan_mask),
                       dbin, out)
        out = xp.where((mt == MISSING_NONE) & nan_mask, zbin, out)
        if self.has_cat:
            # categorical columns: exact-match batched search on host
            # tables (int64 keys; NaN maps to key -1 = bin 0 like the
            # oracle).  Rare columns, always numpy.
            v_np = np.asarray(vals) if xp is not np else vals
            iv = np.where(np.asarray(nan_mask) if xp is not np
                          else nan_mask, -1.0, v_np).astype(np.int64)
            pos = _searchsorted_rows(self.cat_keys, iv, np)
            pos = np.minimum(pos, self.cat_keys.shape[1] - 1)
            f_idx = np.arange(self.num_cols)[None, :]
            hit = self.cat_keys[f_idx, pos] == iv
            miss = np.int32(self.num_bin) if oov_sentinel else 0
            cat_out = np.where(hit, self.cat_bins[f_idx, pos],
                               miss * np.ones((1, self.num_cols),
                                              np.int32))
            is_cat = xp.asarray(self.is_cat)[None, :]
            out = xp.where(is_cat, xp.asarray(cat_out), out)
        return out.astype(xp.int32)


# ---------------------------------------------------------------------------
# EFB conflict counting as one nonzero-mask matmul
# ---------------------------------------------------------------------------


def conflict_matrix(masks: np.ndarray, use_device: bool = False
                    ) -> np.ndarray:
    """(F_sparse, F_sparse) pairwise conflict counts from the 0/1
    non-default-row mask matrix (F_sparse, n_sample): ONE matmul
    M @ M.T replaces the host's per-(feature, bundle) mask-AND loop.
    Diagonal = per-feature non-default counts."""
    m = np.ascontiguousarray(masks, dtype=np.float32)
    if use_device:
        try:
            import jax
            import jax.numpy as jnp
            c = jax.device_get(jnp.matmul(jnp.asarray(m), jnp.asarray(m).T))
            return np.asarray(np.rint(c), dtype=np.int64)
        except Exception as exc:   # pragma: no cover - device-optional
            log.warning("device conflict matmul unavailable (%s); "
                        "using host matmul", str(exc)[:120])
    c = m @ m.T
    # f32 dot of 0/1 vectors is exact below 2^24 samples (n <= 50000)
    return np.asarray(np.rint(c), dtype=np.int64)


# ---------------------------------------------------------------------------
# Direct-to-device (G, N_pad) ingest
# ---------------------------------------------------------------------------


class DeviceIngest:
    """Streams packed (rows, G) host chunks into the learner's
    transposed (G, N_pad) device buffer with double-buffered
    host->device copies: the device_put of chunk k+1 is issued before
    chunk k's update is awaited (JAX async dispatch overlaps the
    transfer with the in-place dynamic_update_slice), and neither the
    full host binned matrix, its transpose, nor the padded copy ever
    materialize on the host."""

    def __init__(self, num_groups: int, num_data: int, dtype,
                 tpu_row_chunk: int):
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp
        self.G = max(int(num_groups), 1)
        self.N = int(num_data)
        self.dtype = np.dtype(dtype)
        self.row_chunk, self.row0, self.n_pad = row_geometry(
            tpu_row_chunk, self.N)
        self.buffer = jnp.zeros((self.G, self.n_pad), self.dtype)
        # in-place chunk write: donation keeps ONE device buffer alive
        self._upd = jax.jit(
            lambda buf, chunk, off: jax.lax.dynamic_update_slice(
                buf, chunk, (0, off)),
            donate_argnums=(0,))
        self._row = 0
        self._pending = None           # (device chunk, offset) in flight
        # single-copy residency handoff: the fused trainer may ADOPT the
        # buffer outright (donating it through its per-iteration step) and
        # leave a recovery callback that reconstructs the original-order
        # layout from its live permuted carrier
        self._recover = None

    def _flush(self):
        if self._pending is not None:
            dev, off = self._pending
            self.buffer = self._upd(self.buffer, dev,
                                    self._jnp.int32(off))
            self._pending = None

    def push(self, packed_rows: np.ndarray) -> None:
        """Append a (rows, G) packed host chunk (row-major, any chunking
        the producer likes)."""
        self.push_t(packed_rows.T)

    def push_t(self, packed_cols: np.ndarray) -> None:
        """Append a (G, rows) packed host chunk — the buffer's native
        orientation, so a feature-major producer pays no transpose."""
        n = packed_cols.shape[1]
        if n == 0:
            return
        if self._row + n > self.N:
            raise ValueError("device ingest overflow: %d rows into %d"
                             % (self._row + n, self.N))
        host_t = np.ascontiguousarray(packed_cols.astype(
            self.dtype, copy=False))
        if host_t.shape[0] < self.G:      # zero usable features edge
            host_t = np.zeros((self.G, n), self.dtype)
        dev = self._jax.device_put(host_t)    # async; overlaps prior upd
        off = self.row0 + self._row
        self._row += n
        self._flush()
        self._pending = (dev, off)

    def finish(self):
        """Seal the buffer; returns the (G, N_pad) device array."""
        if self._row != self.N:
            raise ValueError("device ingest underflow: %d of %d rows"
                             % (self._row, self.N))
        self._flush()
        return self.buffer

    # -- learner handoff -------------------------------------------------
    def matches(self, row_chunk: int, n_pad: int, dtype) -> bool:
        return (self.row_chunk == row_chunk and self.n_pad == n_pad
                and self.dtype == np.dtype(dtype))

    def release_buffer(self, recover) -> None:
        """Hand the buffer to the fused trainer (single-copy residency:
        the trainer's physical carrier becomes the ONLY binned resident
        and is donated in place across iterations).  ``recover()`` must
        return a fresh (G, n_pad) original-order device buffer rebuilt
        from the carrier — it is called lazily by ``host_binned`` /
        ``part0`` when a later consumer (pickle, save_binary, a second
        booster) needs the pristine layout back."""
        self.buffer = None
        self._recover = recover

    def live_buffer(self):
        """The (G, n_pad) device buffer, reconstructing it from the
        adopting trainer's carrier when the buffer was released.  May
        transiently hold 2x the binned footprint (carrier + rebuilt
        buffer) until the caller drops one of them."""
        buf = self.buffer
        if buf is not None and not buf.is_deleted():
            return buf
        if self._recover is None:
            raise ValueError(
                "device ingest buffer was consumed by training and no "
                "recovery callback is installed")
        return self._recover()

    def part0(self, pb_rows: int):
        """The learner-shaped buffer: padded with zero rows on device
        when the Pallas partition wants sublane-aligned extra rows."""
        if self.buffer is None or self.buffer.is_deleted():
            # a previous booster adopted the buffer: restore the pristine
            # layout so this learner starts from the same state
            self.buffer = self.live_buffer()
            self._recover = None
        if pb_rows <= self.buffer.shape[0]:
            return self.buffer
        return self._jnp.pad(self.buffer,
                             ((0, pb_rows - self.buffer.shape[0]), (0, 0)))

    def host_binned(self, block_rows: int = 262144) -> np.ndarray:
        """Materialize the row-major host binned matrix back from the
        device buffer (fallback for consumers that need host bins after
        a host-binned-free construction).

        Streams in bounded row blocks: the peak HOST-side delta beyond
        the (N, G) result is one (G, block) transfer staging buffer plus
        its transpose — not a second full-matrix copy (the full-transfer
        path doubled the host footprint exactly where pickling /
        save_binary are already memory-tight)."""
        import jax
        buf = self.live_buffer()
        # a carrier-recovered buffer may carry extra sublane-pad rows
        # beyond G (learner _pb_rows > G): slice them off
        out = np.empty((self.N, self.G), dtype=self.dtype)
        for lo in range(0, self.N, block_rows):
            hi = min(lo + block_rows, self.N)
            sl = buf[:self.G, self.row0 + lo: self.row0 + hi]
            # deliberate per-block transfer: batching is the hazard
            # here — one get of the whole buffer is exactly the
            # 2x-host-peak this path exists to avoid
            out[lo:hi] = np.asarray(
                jax.device_get(sl)).T    # jaxlint: ok=JL001
        return out
