"""Deterministic mergeable per-feature quantile sketches for
out-of-core bin finding (ISSUE 17 / ROADMAP item 2).

The construction bottleneck for datasets that do not fit host RAM is
the exact bin finder: it wants every (sampled) value of a feature in
one sorted array.  This module replaces that with a sketch in the
spirit of the weighted quantile sketch of arXiv:1806.11248 (XGBoost's
external-memory path), but built so that merging is *canonical*:

* Every non-zero non-NaN float64 value is mapped through an
  order-preserving bijection onto uint64 codes (sign-folded IEEE bits,
  ``_monotone_code``).
* A sketch at ``level`` r keeps, for every occupied cell
  ``code >> r``, the exact value count and the exact **maximum** value
  in the cell.  Counts are additive and max is associative, so cell
  states combine in any order.
* ``level`` starts at 0 — cells are then exact distinct float64
  values with exact counts, and the extracted ``BinMapper`` is
  bit-identical to the exact sort-based oracle.  Only when the number
  of occupied cells exceeds the capacity ``k`` does the level rise
  (cells pairwise-merge, dropping one low bit per step).
* The resting level is *canonical*: the smallest r with at most ``k``
  occupied cells for the value multiset seen so far.  A folded stream
  can never overshoot it (it only coarsens when its running occupancy
  — a lower bound on the union's — exceeds ``k``), and a merge of
  shard sketches aligns to the same point.  The final state is
  therefore a pure function of the value multiset: chunk order, chunk
  boundaries and rank sharding cannot change a single bit of the
  extracted cuts.

Cut extraction feeds the (cell max, cell count) pairs through the SAME
nextafter-merge + greedy equal-count machinery as the exact path
(ops/construct.py ``mapper_from_distinct``).  In the lossy regime
(level > 0) the CDF error of the sketch against the raw stream is
bounded by the heaviest multi-value cell (only the single cell
straddling a query point can be misattributed — cells partition the
value axis into disjoint ordered ranges); ``rank_error_bound`` reports
that bound and tests/test_sketch.py asserts the measured deviation
stays under it.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

import numpy as np

from ..utils import log
from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, K_ZERO_THRESHOLD

DEFAULT_K = 8192


def _monotone_code(vals: np.ndarray) -> np.ndarray:
    """Order-preserving bijection float64 -> uint64 (sign-folded IEEE
    bits): positives get the sign bit set, negatives are bit-flipped so
    more-negative sorts lower.  NaNs must be filtered by the caller."""
    b = np.ascontiguousarray(vals, dtype=np.float64).view(np.int64)
    return np.where(b < 0, ~b, b ^ np.int64(-2 ** 63)).astype(np.uint64)


def _combine(keys: np.ndarray, counts: np.ndarray, maxes: np.ndarray):
    """Collapse duplicate keys: counts sum, maxes max — the (unsorted,
    with-duplicates) -> (sorted unique) normal form.  Both reductions
    are order-independent, so any interleaving of inputs lands here."""
    if len(keys) == 0:
        return keys, counts, maxes
    order = np.argsort(keys, kind="stable")
    k2, c2, m2 = keys[order], counts[order], maxes[order]
    starts = np.flatnonzero(
        np.concatenate([[True], k2[1:] != k2[:-1]]))
    uk = k2[starts]
    uc = np.add.reduceat(c2, starts).astype(np.int64)
    um = np.maximum.reduceat(m2, starts)
    return uk, uc, um


class FeatureSketch:
    """One feature's mergeable value sketch (see module docstring)."""

    __slots__ = ("k", "level", "keys", "counts", "maxes",
                 "nan_cnt", "total_cnt")

    def __init__(self, k: int = DEFAULT_K):
        # k >= 2 guarantees the coarsening loop terminates before the
        # 64-bit code runs out of droppable bits (level <= 63)
        self.k = max(int(k), 2)
        self.level = 0
        self.keys = np.empty(0, np.uint64)
        self.counts = np.empty(0, np.int64)
        self.maxes = np.empty(0, np.float64)
        self.nan_cnt = 0
        self.total_cnt = 0

    # -- accumulation ---------------------------------------------------
    def _coarsen_to_fit(self) -> None:
        while len(self.keys) > self.k:
            self.level += 1
            self.keys, self.counts, self.maxes = _combine(
                self.keys >> np.uint64(1), self.counts, self.maxes)

    def update(self, values) -> None:
        """Fold one raw value chunk (any order, NaN/zero included)."""
        v = np.asarray(values, dtype=np.float64).ravel()
        self.total_cnt += int(v.size)
        if v.size == 0:
            return
        nan = np.isnan(v)
        n_nan = int(np.count_nonzero(nan))
        if n_nan:
            self.nan_cnt += n_nan
            v = v[~nan]
        # |v| <= K_ZERO_THRESHOLD is the implied-zero bin, tracked by
        # count only (zero_cnt = total - nan - sum(counts)), exactly
        # like the exact path's sparse sampling
        v = v[np.abs(v) > K_ZERO_THRESHOLD]
        if v.size == 0:
            return
        v = np.sort(v)
        keys = _monotone_code(v) >> np.uint64(self.level)
        # v ascending => codes ascending => per-key groups contiguous:
        # group count by run length, group max = run's last element
        starts = np.flatnonzero(
            np.concatenate([[True], keys[1:] != keys[:-1]]))
        bounds = np.concatenate([starts, [len(keys)]])
        uk = keys[starts]
        uc = np.diff(bounds).astype(np.int64)
        um = v[bounds[1:] - 1]
        if len(self.keys) == 0:
            self.keys, self.counts, self.maxes = uk, uc, um
        else:
            self.keys, self.counts, self.maxes = _combine(
                np.concatenate([self.keys, uk]),
                np.concatenate([self.counts, uc]),
                np.concatenate([self.maxes, um]))
        self._coarsen_to_fit()

    @classmethod
    def merge(cls, sketches: Sequence["FeatureSketch"]) -> "FeatureSketch":
        """Canonical multiset merge of shard/chunk sketches: the result
        is bit-identical for ANY partitioning or ordering of the same
        value stream (tests/test_sketch.py permutes and re-shards)."""
        sketches = list(sketches)
        if not sketches:
            return cls()
        out = cls(sketches[0].k)
        if any(s.k != out.k for s in sketches):
            raise ValueError("cannot merge sketches with different k")
        out.total_cnt = sum(s.total_cnt for s in sketches)
        out.nan_cnt = sum(s.nan_cnt for s in sketches)
        lvl = max(s.level for s in sketches)
        parts = [(s.keys >> np.uint64(lvl - s.level), s.counts, s.maxes)
                 for s in sketches if len(s.keys)]
        if parts:
            out.level = lvl
            out.keys, out.counts, out.maxes = _combine(
                np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]),
                np.concatenate([p[2] for p in parts]))
            out._coarsen_to_fit()
        return out

    # -- extraction -----------------------------------------------------
    @property
    def zero_cnt(self) -> int:
        return int(self.total_cnt - self.nan_cnt - int(self.counts.sum()))

    def rank_error_bound(self) -> int:
        """Worst-case CDF miscount vs the raw stream: only the one cell
        straddling a query value can be misattributed, and exact cells
        (level 0) or single-value cells cannot err at all."""
        if self.level == 0 or len(self.counts) == 0:
            return 0
        multi = self.counts[self.counts > 1]
        return int(multi.max()) if len(multi) else 0

    def rank_upto(self, x: float) -> int:
        """Sketch CDF: count of non-NaN values <= x (zeros included) —
        the quantity the rank-error bound is asserted against."""
        i = int(np.searchsorted(self.maxes, x, side="right"))
        r = int(self.counts[:i].sum())
        if x >= 0.0:
            r += self.zero_cnt
        return r

    def to_mapper(self, max_bin: int, min_data_in_bin: int = 3,
                  min_split_data: int = 0, pre_filter: bool = False,
                  bin_type: int = BIN_NUMERICAL, use_missing: bool = True,
                  zero_as_missing: bool = False,
                  forced_upper_bounds: Optional[List[float]] = None):
        """The feature's BinMapper via the SAME distinct+counts tail as
        the exact path (ops/construct.py mapper_from_distinct) — at
        level 0 the inputs are the exact distinct values and counts, so
        the mapper is bit-identical to the sort-based oracle."""
        from .construct import _distinct_from_sorted, mapper_from_distinct
        if bin_type == BIN_CATEGORICAL and self.level > 0:
            # a coarsened cell folds several category ids into one max:
            # silently mis-binning categories is never acceptable
            raise ValueError(
                "categorical feature overflowed the sketch (more than "
                "sketch_k=%d distinct values); raise sketch_k or use "
                "bin_construct_mode=exact" % self.k)
        zero_cnt = self.zero_cnt
        if len(self.maxes) == 0 and zero_cnt == 0:
            # mirror find_bin_sorted's empty-feature special case: the
            # zero distinct is emitted with a zero count
            distinct = np.asarray([0.0])
            counts = np.asarray([0], dtype=np.int64)
        else:
            distinct, counts = _distinct_from_sorted(
                self.maxes, zero_cnt, counts=self.counts)
        return mapper_from_distinct(
            distinct, counts, na_cnt=self.nan_cnt,
            total_sample_cnt=self.total_cnt, max_bin=max_bin,
            min_data_in_bin=min_data_in_bin, min_split_data=min_split_data,
            pre_filter=pre_filter, bin_type=bin_type,
            use_missing=use_missing, zero_as_missing=zero_as_missing,
            forced_upper_bounds=forced_upper_bounds)


class SketchSet:
    """All features' sketches for one dataset (or one rank's row shard),
    with a compact binary serialization for the rank allgather."""

    def __init__(self, num_features: int, k: int = DEFAULT_K):
        self.k = max(int(k), 2)
        self.sketches = [FeatureSketch(self.k)
                         for _ in range(int(num_features))]

    def __len__(self) -> int:
        return len(self.sketches)

    def update_chunk(self, chunk: np.ndarray) -> None:
        """Fold one (rows, F) raw chunk, column by column."""
        chunk = np.asarray(chunk, dtype=np.float64)
        if chunk.ndim == 1:
            chunk = chunk.reshape(1, -1)
        if chunk.shape[1] != len(self.sketches):
            raise ValueError("chunk has %d features, sketch set has %d"
                             % (chunk.shape[1], len(self.sketches)))
        for f in range(chunk.shape[1]):
            self.sketches[f].update(chunk[:, f])

    @classmethod
    def merge(cls, sets: Sequence["SketchSet"]) -> "SketchSet":
        sets = list(sets)
        if not sets:
            return cls(0)
        nf = max(len(s) for s in sets)
        out = cls(0, sets[0].k)
        out.sketches = [
            FeatureSketch.merge([s.sketches[f] for s in sets
                                 if f < len(s)])
            for f in range(nf)]
        return out

    # -- wire format (parallel/distributed.py allgather) ----------------
    def serialize(self) -> bytes:
        """Header JSON + concatenated cell arrays.  No pickle: the
        payload crosses rank boundaries."""
        header = {
            "k": self.k,
            "features": [{"level": s.level, "cells": len(s.keys),
                          "nan": s.nan_cnt, "total": s.total_cnt}
                         for s in self.sketches],
        }
        keys = (np.concatenate([s.keys for s in self.sketches])
                if self.sketches else np.empty(0, np.uint64))
        counts = (np.concatenate([s.counts for s in self.sketches])
                  if self.sketches else np.empty(0, np.int64))
        maxes = (np.concatenate([s.maxes for s in self.sketches])
                 if self.sketches else np.empty(0, np.float64))
        return (json.dumps(header, separators=(",", ":")).encode()
                + b"\x00" + keys.astype("<u8").tobytes()
                + counts.astype("<i8").tobytes()
                + maxes.astype("<f8").tobytes())

    @classmethod
    def deserialize(cls, payload: bytes) -> "SketchSet":
        head, body = payload.split(b"\x00", 1)
        header = json.loads(head.decode())
        feats = header["features"]
        out = cls(len(feats), header["k"])
        ncell = sum(int(f["cells"]) for f in feats)
        keys = np.frombuffer(body, "<u8", count=ncell, offset=0)
        counts = np.frombuffer(body, "<i8", count=ncell, offset=8 * ncell)
        maxes = np.frombuffer(body, "<f8", count=ncell, offset=16 * ncell)
        pos = 0
        for s, f in zip(out.sketches, feats):
            n = int(f["cells"])
            s.level = int(f["level"])
            s.nan_cnt = int(f["nan"])
            s.total_cnt = int(f["total"])
            s.keys = keys[pos:pos + n].astype(np.uint64)
            s.counts = counts[pos:pos + n].astype(np.int64)
            s.maxes = maxes[pos:pos + n].astype(np.float64)
            pos += n
        return out

    def memory_bytes(self) -> int:
        return sum(s.keys.nbytes + s.counts.nbytes + s.maxes.nbytes
                   for s in self.sketches)


def resolve_bin_mode(config, num_data: int) -> str:
    """'exact' or 'sketch' from ``bin_construct_mode`` ('auto' switches
    to the sketch path above ``sketch_row_threshold`` rows, where the
    exact path's full-sample sort and the raw matrix both stop being
    cheap)."""
    mode = str(getattr(config, "bin_construct_mode", "auto")
               or "auto").lower()
    if mode not in ("auto", "exact", "sketch"):
        log.warning("bin_construct_mode=%s unknown; using 'auto'", mode)
        mode = "auto"
    if mode == "auto":
        thr = int(getattr(config, "sketch_row_threshold", 1_000_000)
                  or 1_000_000)
        return "sketch" if int(num_data) > thr else "exact"
    return mode
