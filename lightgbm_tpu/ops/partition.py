"""Leaf partition on TPU.

TPU-native replacement for the reference DataPartition
(src/treelearner/data_partition.hpp) and the CUDA bitvector+prefix-sum path
(src/treelearner/cuda/cuda_data_partition.cu:288-907).  TPUs have no fast
scatter, so the stable two-way partition of a leaf's row-index range is done
with one stable sort over a power-of-two bucket slice:

  key 0 = goes left, key 1 = goes right, key 2 = padding (rows of *other*
  leaves inside the bucket slice).  A stable sort groups left/right blocks in
  original order and leaves the padding rows in their original trailing
  positions, so the slice can be written back in place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


def split_decision(bin_values: jnp.ndarray, threshold, default_left,
                   missing_type, default_bin, nan_bin) -> jnp.ndarray:
    """Per-row goes-left decision for a numerical split.

    reference: DenseBin::Split (src/io/dense_bin.hpp:237-310) — values in the
    missing bin follow ``default_left``; otherwise bin <= threshold goes left.
    """
    b = bin_values.astype(jnp.int32)
    is_missing = jnp.where(
        missing_type == MISSING_ZERO, b == default_bin,
        jnp.where(missing_type == MISSING_NAN, b == nan_bin, False))
    natural = b <= threshold
    return jnp.where(is_missing, default_left, natural)


def window_order(goes_left: jnp.ndarray, valid: jnp.ndarray, width: int):
    """Compaction permutation of one ``width``-row window: lefts pack
    forward in encounter order, rights follow at ``[nl, nl+nr)`` in
    encounter order, invalid (other-leaf / padding) rows park past the
    live region.  Returns (order, left_count).

    Byte-compatible with the chunked scatter+copyback path's SINGLE-
    chunk case at any width — the leaf-size-adaptive policy's exactness
    contract (ops/chunkpolicy.py): the move is an integer packed-key
    sort + gather, so a leaf that fits one window produces the same
    final row order whether that window is the base chunk or a smaller
    menu width.
    """
    chunk_bits = width.bit_length() - 1
    if width & (width - 1):
        raise ValueError(f"window width {width} must be a power of two")
    gl = goes_left & valid
    gr = valid & ~gl
    gli = gl.astype(jnp.int32)
    gri = gr.astype(jnp.int32)
    inv = (~valid).astype(jnp.int32)
    nlc = jnp.sum(gli)
    nrc = jnp.sum(gri)
    lrank = jnp.cumsum(gli) - gli
    rrank = jnp.cumsum(gri) - gri
    irank = jnp.cumsum(inv) - inv
    dloc = jnp.where(gl, lrank,
                     jnp.where(gr, nlc + rrank, nlc + nrc + irank))
    iot = jax.lax.iota(jnp.int32, width)
    # single-operand sort of packed (dest << log2W) | src keys — the
    # multi-operand sort jnp.argsort lowers to is the slow path
    packed = ((dloc << chunk_bits) | iot).astype(jnp.uint32)
    order = (jax.lax.sort(packed) & jnp.uint32(width - 1)).astype(jnp.int32)
    return order, nlc


def partition_leaf(indices: jnp.ndarray, binned_col_getter, start, count,
                   size: int, goes_left_of_rows):
    """Stably partition one leaf's index range in place.

    Args:
      indices: (N_pad,) int32 partition array (padded with sentinel rows).
      binned_col_getter: unused here; decision comes via ``goes_left_of_rows``.
      start: dynamic slice start.
      count: dynamic number of valid rows in the leaf.
      size: static bucket size (power of two >= count).
      goes_left_of_rows: fn(row_ids (size,)) -> bool (size,).

    Returns (new_indices, left_count).
    """
    idx = jax.lax.dynamic_slice(indices, (start,), (size,))
    pos = jax.lax.iota(jnp.int32, size)
    valid = pos < count
    goes_left = goes_left_of_rows(idx) & valid
    key = jnp.where(valid, jnp.where(goes_left, 0, 1), 2).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    new_idx = jnp.take(idx, order)
    out = jax.lax.dynamic_update_slice(indices, new_idx, (start,))
    left_count = jnp.sum(goes_left.astype(jnp.int32))
    return out, left_count
