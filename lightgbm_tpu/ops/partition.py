"""Leaf partition on TPU.

TPU-native replacement for the reference DataPartition
(src/treelearner/data_partition.hpp) and the CUDA bitvector+prefix-sum path
(src/treelearner/cuda/cuda_data_partition.cu:288-907).  TPUs have no fast
scatter, so the stable two-way partition of a leaf's row-index range is done
with one stable sort over a power-of-two bucket slice:

  key 0 = goes left, key 1 = goes right, key 2 = padding (rows of *other*
  leaves inside the bucket slice).  A stable sort groups left/right blocks in
  original order and leaves the padding rows in their original trailing
  positions, so the slice can be written back in place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


def split_decision(bin_values: jnp.ndarray, threshold, default_left,
                   missing_type, default_bin, nan_bin) -> jnp.ndarray:
    """Per-row goes-left decision for a numerical split.

    reference: DenseBin::Split (src/io/dense_bin.hpp:237-310) — values in the
    missing bin follow ``default_left``; otherwise bin <= threshold goes left.
    """
    b = bin_values.astype(jnp.int32)
    is_missing = jnp.where(
        missing_type == MISSING_ZERO, b == default_bin,
        jnp.where(missing_type == MISSING_NAN, b == nan_bin, False))
    natural = b <= threshold
    return jnp.where(is_missing, default_left, natural)


def partition_leaf(indices: jnp.ndarray, binned_col_getter, start, count,
                   size: int, goes_left_of_rows):
    """Stably partition one leaf's index range in place.

    Args:
      indices: (N_pad,) int32 partition array (padded with sentinel rows).
      binned_col_getter: unused here; decision comes via ``goes_left_of_rows``.
      start: dynamic slice start.
      count: dynamic number of valid rows in the leaf.
      size: static bucket size (power of two >= count).
      goes_left_of_rows: fn(row_ids (size,)) -> bool (size,).

    Returns (new_indices, left_count).
    """
    idx = jax.lax.dynamic_slice(indices, (start,), (size,))
    pos = jax.lax.iota(jnp.int32, size)
    valid = pos < count
    goes_left = goes_left_of_rows(idx) & valid
    key = jnp.where(valid, jnp.where(goes_left, 0, 1), 2).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    new_idx = jnp.take(idx, order)
    out = jax.lax.dynamic_update_slice(indices, new_idx, (start,))
    left_count = jnp.sum(goes_left.astype(jnp.int32))
    return out, left_count
