"""Best-split search over histograms, vectorized across (feature, bin).

TPU-native replacement for the reference's per-feature sequential threshold
scan (src/treelearner/feature_histogram.hpp FindBestThresholdSequentially:830,
GetSplitGains:759, CalculateSplittedLeafOutput:717) and the CUDA best-split
kernels (src/treelearner/cuda/cuda_best_split_finder.cu): the forward/reverse
accumulations become masked cumulative sums over the bin axis, gains are
evaluated for every (feature, bin, direction) candidate at once on the VPU,
and the arg-max reduction reproduces the reference's scan-order tie-breaking:

  * reverse scan runs "first" (forward replaces only on strictly-greater gain),
  * within the reverse scan larger thresholds win ties,
  * within the forward scan smaller thresholds win ties,
  * across features the smaller feature index wins ties.

Missing-value handling mirrors the reference dispatch
(feature_histogram.hpp FuncForNumricalL3:272-455):
  * MissingType::Zero  -> both scans skip the default(zero) bin; zeros follow
    ``default_left`` (reverse scan => default_left=True).
  * MissingType::NaN   -> the last bin holds NaNs; the reverse scan keeps it
    out of the right side (NaN defaults left), the forward scan keeps it right.
  * MissingType::None  -> single reverse scan, no skipping.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitContext(NamedTuple):
    """Static per-feature metadata, device-resident (shapes (F,))."""
    num_bin: jnp.ndarray        # int32
    missing_type: jnp.ndarray   # int32
    default_bin: jnp.ndarray    # int32
    is_categorical: jnp.ndarray  # int32 (categorical handled separately)
    feature_index: jnp.ndarray  # int32 original feature id (for reporting)


class BestSplit(NamedTuple):
    gain: jnp.ndarray           # f32 scalar, relative gain (already minus shift)
    feature: jnp.ndarray        # int32, index into the used-feature enumeration
    threshold: jnp.ndarray      # int32 bin threshold
    default_left: jnp.ndarray   # bool
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    left_count: jnp.ndarray     # int32 (hessian-estimated, like the reference)
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray
    is_cat: jnp.ndarray         # bool — categorical split
    cat_set: jnp.ndarray        # (BF,) bool — feature-local bins going LEFT


class BestSplitLinear(NamedTuple):
    """``BestSplit`` plus the searched leaf's OWN fitted linear model
    ``value(x) = const + coeff * x`` (linear_tree_mode=leafwise_gain):
    the best whole-leaf single-feature fit, read off the same moment
    prefix sums the candidate scan uses (last cumsum entry per feature
    = whole-leaf totals — zero extra passes).  This model is what the
    leaf predicts with if it is never split again, and its gain is the
    shift the split candidates must beat.  ``left_output`` /
    ``right_output`` keep the constant outputs — they stay the NaN-row
    fallback value of the linear leaves."""
    gain: jnp.ndarray
    feature: jnp.ndarray
    threshold: jnp.ndarray
    default_left: jnp.ndarray
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    left_count: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray
    is_cat: jnp.ndarray
    cat_set: jnp.ndarray
    self_const: jnp.ndarray     # f32 — this leaf's model intercept
    self_coeff: jnp.ndarray     # f32 — this leaf's model slope
    self_feature: jnp.ndarray   # int32 — ORIGINAL feature id of the model


def _threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(0.0, jnp.abs(s) - l1)


def leaf_output(sum_g, sum_h, l1, l2, max_delta_step):
    """reference: CalculateSplittedLeafOutput (feature_histogram.hpp:717)."""
    ret = -_threshold_l1(sum_g, l1) / (sum_h + l2)
    if max_delta_step > 0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    return ret


def _leaf_gain_given_output(sum_g, sum_h, l1, l2, out):
    sg = _threshold_l1(sum_g, l1)
    return -(2.0 * sg * out + (sum_h + l2) * out * out)


def leaf_gain(sum_g, sum_h, l1, l2, max_delta_step):
    """reference: GetLeafGain (feature_histogram.hpp:800)."""
    if max_delta_step > 0:
        out = leaf_output(sum_g, sum_h, l1, l2, max_delta_step)
        return _leaf_gain_given_output(sum_g, sum_h, l1, l2, out)
    sg = _threshold_l1(sum_g, l1)
    return sg * sg / (sum_h + l2)


def find_best_split_categorical(feat_hist: jnp.ndarray, ctx: SplitContext,
                                sum_g, sum_h_tot, num_data,
                                l1: float, l2: float, max_delta_step: float,
                                min_gain_shift, min_data_in_leaf: int,
                                min_sum_hessian: float,
                                max_cat_threshold: int, cat_l2: float,
                                cat_smooth: float, max_cat_to_onehot: int,
                                min_data_per_group: int,
                                cmin=None, cmax=None):
    """Per-feature best categorical split, vectorized over (feature, bin).

    Mirrors FindBestThresholdCategoricalInner
    (src/treelearner/feature_histogram.cpp:144-340):
      * one-vs-rest when ``num_bin <= max_cat_to_onehot`` (plain lambda_l2);
      * otherwise bins with estimated count >= cat_smooth are sorted ascending
        by ``sum_g / (sum_h + cat_smooth)`` and prefix sets are scanned from
        both ends (at most ``min(max_cat_threshold, (used+1)/2)`` categories),
        with ``lambda_l2 + cat_l2`` regularization and candidate evaluation
        gated on ``min_data_per_group`` rows accumulated since the previous
        candidate;
      * bin 0 (the NaN/other bin) is never part of the left set, so missing
        and unseen categories always go right (default_left=false).

    The sequential C++ scan becomes masked cumulative sums along the sorted
    bin axis plus one short `lax.scan` carrying the per-feature
    ``cnt_cur_group`` counter; break conditions (monotone in the scan
    position) become cumulative-max masks.

    Returns per-feature arrays: (gain (F,), member (F, BF) bool,
    left_g, left_h_incl_eps, left_count, l2_eff (F,)).
    """
    F, BF, _ = feat_hist.shape
    G = feat_hist[..., 0]
    H = feat_hist[..., 1]
    cnt_factor = num_data / sum_h_tot
    l2c = l2 + cat_l2

    def pair_gain(lg, lh, rg, rh, l2_eff):
        """Two-sided gain; with monotone bounds active the child outputs are
        clipped to [cmin, cmax] first (reference: constrained
        CalculateSplittedLeafOutput + GetLeafGainGivenOutput)."""
        if cmin is None:
            return (leaf_gain(lg, lh, l1, l2_eff, max_delta_step) +
                    leaf_gain(rg, rh, l1, l2_eff, max_delta_step))
        lo = jnp.clip(leaf_output(lg, lh, l1, l2_eff, max_delta_step),
                      cmin, cmax)
        ro = jnp.clip(leaf_output(rg, rh, l1, l2_eff, max_delta_step),
                      cmin, cmax)
        return (_leaf_gain_given_output(lg, lh, l1, l2_eff, lo) +
                _leaf_gain_given_output(rg, rh, l1, l2_eff, ro))

    bins = jax.lax.broadcasted_iota(jnp.int32, (F, BF), 1)
    nb = ctx.num_bin[:, None]
    in_range = (bins >= 1) & (bins < nb)
    cnt_bin = jnp.floor(H * cnt_factor + 0.5).astype(jnp.int32) * in_range
    num_data_i = num_data.astype(jnp.int32) if hasattr(num_data, "astype") \
        else jnp.int32(num_data)

    use_onehot = ctx.num_bin <= max_cat_to_onehot        # (F,)

    # ---- one-vs-rest (feature_histogram.cpp:184-239) ----
    hess_t = H + K_EPSILON
    other_g = sum_g - G
    other_h = sum_h_tot - H - K_EPSILON
    other_cnt = num_data_i - cnt_bin
    gain_oh = pair_gain(G, hess_t, other_g, other_h, l2)
    valid_oh = (in_range & (cnt_bin >= min_data_in_leaf) &
                (H >= min_sum_hessian) & (other_cnt >= min_data_in_leaf) &
                (other_h >= min_sum_hessian) & (gain_oh > min_gain_shift))
    gain_oh = jnp.where(valid_oh, gain_oh, K_MIN_SCORE)
    best_oh = jnp.argmax(gain_oh, axis=1)                 # (F,)
    best_oh_gain = jnp.take_along_axis(gain_oh, best_oh[:, None], 1)[:, 0]
    member_oh = bins == best_oh[:, None]

    # ---- sorted prefix sets (feature_histogram.cpp:240-339) ----
    valid_s = in_range & (cnt_bin.astype(jnp.float32) >= cat_smooth)
    ratio = jnp.where(valid_s, G / (H + cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1, stable=True)       # ascending
    inv_rank = jnp.argsort(order, axis=1, stable=True)    # bin -> sorted pos
    used = valid_s.sum(axis=1).astype(jnp.int32)          # (F,)
    max_num_cat = jnp.minimum(jnp.int32(max_cat_threshold), (used + 1) // 2)

    sG = jnp.take_along_axis(jnp.where(valid_s, G, 0.0), order, axis=1)
    sH = jnp.take_along_axis(jnp.where(valid_s, H, 0.0), order, axis=1)
    sC = jnp.take_along_axis(jnp.where(valid_s, cnt_bin, 0), order, axis=1)
    pg = jnp.cumsum(sG, axis=1)
    ph = jnp.cumsum(sH, axis=1)
    pc = jnp.cumsum(sC, axis=1)
    tvg = pg[:, -1:]
    tvh = ph[:, -1:]
    tvc = pc[:, -1:]

    pos = jax.lax.broadcasted_iota(jnp.int32, (F, BF), 1)

    def prefix_at(p, idx):
        """p[:, idx] with idx == -1 -> 0 (idx is (F, BF) int32)."""
        v = jnp.take_along_axis(p, jnp.maximum(idx, 0), axis=1)
        return jnp.where(idx >= 0, v, jnp.zeros_like(v))

    # forward (dir=+1): left set = sorted[0..i]
    lg_f = pg
    lh_f = ph + K_EPSILON
    lc_f = pc
    # reverse (dir=-1): left set = sorted[used-1-i .. used-1]
    rev_idx = used[:, None] - 2 - pos
    lg_r = tvg - prefix_at(pg, rev_idx)
    lh_r = tvh - prefix_at(ph, rev_idx) + K_EPSILON
    lc_r = tvc - prefix_at(pc, rev_idx)

    in_loop = (pos < used[:, None]) & (pos < max_num_cat[:, None])
    # per-step counts in each direction's visit order: forward visits sorted
    # position i at step i, reverse visits sorted position used-1-i
    step_cnt_fwd = sC
    step_cnt_rev = prefix_at(pc, used[:, None] - 1 - pos) - \
        prefix_at(pc, used[:, None] - 2 - pos)

    def candidates(lg, lh, lc, step_cnt):
        rg = sum_g - lg
        rh = sum_h_tot - lh
        rc = num_data_i - lc
        left_ok = (lc >= min_data_in_leaf) & (lh >= min_sum_hessian)
        broken = ((rc < min_data_in_leaf) | (rc < min_data_per_group) |
                  (rh < min_sum_hessian))
        not_broken = jnp.cumsum(broken.astype(jnp.int32), axis=1) == 0

        # cnt_cur_group gate: scan along the sorted axis, carry (F,) counter
        def step(c, xs):
            cnt_i, ok_i = xs
            c = c + cnt_i
            ev = ok_i & (c >= min_data_per_group)
            return jnp.where(ev, 0, c), ev

        # the carry derives from the (possibly device-varying) inputs so
        # shard_map's vma typing accepts the scan (a constant zero carry
        # is unvarying and trips "carry input/output types differ")
        carry0 = (step_cnt[:, 0] * 0).astype(jnp.int32)
        _, ev = jax.lax.scan(
            step, carry0,
            (step_cnt.T, (left_ok & not_broken & in_loop).T))
        evaluated = ev.T
        gain = pair_gain(lg, lh, rg, rh, l2c)
        gain = jnp.where(evaluated & (gain > min_gain_shift),
                         gain, K_MIN_SCORE)
        return gain

    gain_fwd = candidates(lg_f, lh_f, lc_f, step_cnt_fwd)
    gain_rev = candidates(lg_r, lh_r, lc_r, step_cnt_rev)
    best_i_f = jnp.argmax(gain_fwd, axis=1)               # first wins ties
    best_g_f = jnp.take_along_axis(gain_fwd, best_i_f[:, None], 1)[:, 0]
    best_i_r = jnp.argmax(gain_rev, axis=1)
    best_g_r = jnp.take_along_axis(gain_rev, best_i_r[:, None], 1)[:, 0]
    use_rev = best_g_r > best_g_f                         # dir=+1 wins ties
    best_sorted_gain = jnp.where(use_rev, best_g_r, best_g_f)
    k = jnp.where(use_rev, best_i_r, best_i_f) + 1        # num cats in set
    member_fwd = inv_rank < k[:, None]
    member_rev = (inv_rank >= used[:, None] - k[:, None]) & \
                 (inv_rank < used[:, None])
    member_sorted = jnp.where(use_rev[:, None], member_rev, member_fwd) & valid_s

    # ---- merge the two modes (exclusive per feature) ----
    gain_c = jnp.where(use_onehot, best_oh_gain, best_sorted_gain)
    member = jnp.where(use_onehot[:, None], member_oh, member_sorted)
    oh_g = jnp.take_along_axis(G, best_oh[:, None], 1)[:, 0]
    oh_h = jnp.take_along_axis(H, best_oh[:, None], 1)[:, 0] + K_EPSILON
    oh_c = jnp.take_along_axis(cnt_bin, best_oh[:, None], 1)[:, 0]
    sel = lambda a_f, a_r: jnp.where(  # noqa: E731
        use_rev, jnp.take_along_axis(a_r, best_i_r[:, None], 1)[:, 0],
        jnp.take_along_axis(a_f, best_i_f[:, None], 1)[:, 0])
    lg_c = jnp.where(use_onehot, oh_g, sel(lg_f, lg_r))
    lh_c = jnp.where(use_onehot, oh_h, sel(lh_f, lh_r))
    lc_c = jnp.where(use_onehot, oh_c, sel(lc_f, lc_r).astype(jnp.int32))
    l2_eff = jnp.where(use_onehot, l2, l2c)
    return gain_c, member, lg_c, lh_c, lc_c, l2_eff


def find_best_split_fast(feat_hist: jnp.ndarray, ctx: SplitContext,
                         sum_g, sum_h, num_data,
                         l1: float, l2: float, max_delta_step: float,
                         min_gain_to_split: float, min_data_in_leaf: int,
                         min_sum_hessian: float,
                         feature_mask: jnp.ndarray | None = None,
                         rand_bins: jnp.ndarray | None = None,
                         feature_contri: jnp.ndarray | None = None):
    """Lean all-numerical best-split search.

    Bit-identical to ``find_best_split`` for plain configs (no
    categorical / monotone / CEGB / path smoothing / voting gains), but
    restructured for HLO op count — the per-split fixed cost of the tree
    loop on TPU is op-dispatch-bound (PERF.md), not FLOP-bound:

      * ONE stacked cumulative sum over a (6, F, BF) tensor replaces the
        six per-stat scans;
      * the reference's scan-order tie-breaking
        (FindBestThresholdSequentially, feature_histogram.hpp:830 — the
        reverse scan first, larger thresholds winning reverse ties,
        smaller forward ties, smaller feature index across features)
        is encoded into the candidate ORDER of one (F, 2*BF) gain
        matrix — per feature the reverse scan's thresholds descending,
        then the forward scan's ascending — so a single flat arg-max
        replaces the per-feature/per-direction arg-max cascade;
      * the winner's statistics ride one packed (4, F*2*BF) matrix read
        with a single lane-dynamic slice.

    Counts ride the f32 cumsum (exact for leaves below 2^24 rows; the
    caller gates on dataset size).
    """
    F, BF, _ = feat_hist.shape
    G = feat_hist[..., 0]
    H = feat_hist[..., 1]
    sum_h_tot = sum_h + 2 * K_EPSILON
    num_data = num_data.astype(jnp.float32) if hasattr(num_data, "astype") \
        else jnp.float32(num_data)
    cnt_factor = num_data / sum_h_tot

    bins = jax.lax.broadcasted_iota(jnp.int32, (F, BF), 1)
    nb = ctx.num_bin[:, None]
    in_range = bins < nb
    missing = ctx.missing_type[:, None]
    dflt = ctx.default_bin[:, None]
    is_zero_miss = missing == MISSING_ZERO
    is_nan_miss = missing == MISSING_NAN
    two_scan = (nb > 2) & (missing != MISSING_NONE)
    cnt_bin = jnp.floor(H * cnt_factor + 0.5) * in_range      # f32, exact

    mask_f = in_range & ~(is_zero_miss & (bins == dflt))
    bmax = nb - 1 - (is_nan_miss & two_scan).astype(jnp.int32)
    mask_r = (in_range & ~(two_scan & is_zero_miss & (bins == dflt)) &
              (bins <= bmax))

    z = jnp.float32(0.0)
    stacked = jnp.stack([
        jnp.where(mask_f, G, z), jnp.where(mask_f, H, z),
        jnp.where(mask_f, cnt_bin, z),
        jnp.where(mask_r, G, z), jnp.where(mask_r, H, z),
        jnp.where(mask_r, cnt_bin, z)])                       # (6, F, BF)
    if jax.default_backend() == "tpu":
        # prefix sums as ONE inclusive lower-triangular matmul on the
        # MXU: XLA's cumsum lowering costs a log-depth pass cascade per
        # operand, and the per-split cost on TPU is op-DISPATCH-bound.
        # f32 dot keeps integer counts exact below 2^24; g/h sums round
        # differently from a serial scan by at most the usual f32
        # dot-product reassociation.
        tri = (jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 0) <=
               jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 1)
               ).astype(jnp.float32)
        cs = jax.lax.dot_general(
            stacked, tri, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (6, F, BF)
    else:
        # off-TPU the triangular matmul is O(F*BF^2) of REAL work — it
        # dominated the CPU host's per-iteration fixed cost (~44 MFLOP
        # per split at F=28, BF=255: ~60% of the 65k-row iteration,
        # PERF.md round 12) — where the log-depth cumsum is O(F*BF)
        cs = jnp.cumsum(stacked, axis=2)                      # (6, F, BF)

    left_g_f = cs[0]
    left_h_f = cs[1] + K_EPSILON
    left_c_f = cs[2]
    right_g_f = sum_g - left_g_f
    right_h_f = sum_h_tot - left_h_f
    right_c_f = num_data - left_c_f

    right_g_r = cs[3, :, -1:] - cs[3]
    right_h_r = cs[4, :, -1:] - cs[4] + K_EPSILON
    right_c_r = cs[5, :, -1:] - cs[5]
    left_g_r = sum_g - right_g_r
    left_h_r = sum_h_tot - right_h_r
    left_c_r = num_data - right_c_r

    gain_f = (leaf_gain(left_g_f, left_h_f, l1, l2, max_delta_step) +
              leaf_gain(right_g_f, right_h_f, l1, l2, max_delta_step))
    gain_r = (leaf_gain(left_g_r, left_h_r, l1, l2, max_delta_step) +
              leaf_gain(right_g_r, right_h_r, l1, l2, max_delta_step))

    gain_shift = leaf_gain(sum_g, sum_h_tot, l1, l2, max_delta_step)
    min_gain_shift = gain_shift + min_gain_to_split
    mdl = jnp.float32(min_data_in_leaf)

    def common_valid(lc, rc, lh, rh):
        return ((lc >= mdl) & (rc >= mdl) &
                (lh >= min_sum_hessian) & (rh >= min_sum_hessian))

    valid_f = (two_scan & in_range & (bins <= nb - 2) &
               ~(is_zero_miss & (bins == dflt)) &
               common_valid(left_c_f, right_c_f, left_h_f, right_h_f) &
               (gain_f > min_gain_shift))
    valid_r = (in_range & (bins <= bmax - 1) &
               ~(two_scan & is_zero_miss & (bins == dflt - 1)) &
               common_valid(left_c_r, right_c_r, left_h_r, right_h_r) &
               (gain_r > min_gain_shift))
    if feature_mask is not None:
        valid_f &= feature_mask[:, None]
        valid_r &= feature_mask[:, None]
    if rand_bins is not None:
        # extra_trees: each feature evaluates ONE random threshold
        # (feature_histogram.hpp USE_RAND arms, rand_threshold)
        at_rand = bins == rand_bins[:, None]
        valid_f &= at_rand
        valid_r &= at_rand

    neg = jnp.float32(K_MIN_SCORE)
    if feature_contri is not None:
        # per-feature gain scaling (feature_histogram.hpp:174
        # `output->gain *= meta_->penalty`): candidates compete on the
        # SCALED relative gain, so the flat argmax runs on it directly
        fc = feature_contri[:, None]
        cand_f = jnp.where(valid_f, (gain_f - min_gain_shift) * fc, neg)
        cand_r = jnp.where(valid_r, (gain_r - min_gain_shift) * fc, neg)
    else:
        cand_f = jnp.where(valid_f, gain_f, neg)
        cand_r = jnp.where(valid_r, gain_r, neg)
    # candidate order encodes the tie-breaking (see docstring)
    gains = jnp.concatenate([cand_r[:, ::-1], cand_f], axis=1)
    # default_left: reverse scan => True, except single-scan NaN features
    dl_r = jnp.broadcast_to((two_scan | ~is_nan_miss).astype(jnp.float32),
                            (F, BF))
    stats = jnp.stack([
        jnp.concatenate([left_g_r[:, ::-1], left_g_f], axis=1),
        jnp.concatenate([left_h_r[:, ::-1], left_h_f], axis=1),
        jnp.concatenate([left_c_r[:, ::-1], left_c_f], axis=1),
        jnp.concatenate([dl_r, jnp.zeros((F, BF), jnp.float32)], axis=1),
    ]).reshape(4, F * 2 * BF)

    flat = gains.reshape(F * 2 * BF)
    widx = jnp.argmax(flat).astype(jnp.int32)
    best_gain = flat[widx]
    picked = jax.lax.dynamic_slice(stats, (0, widx), (4, 1))[:, 0]
    lg, lh, lc_f32, dl = picked[0], picked[1], picked[2], picked[3]

    per_f = 2 * BF
    best_f = widx // per_f
    r = widx - best_f * per_f
    best_t = jnp.where(r < BF, BF - 1 - r, r - BF)

    rg = sum_g - lg
    rh = sum_h_tot - lh
    rc = num_data - lc_f32
    args = (l1, l2, max_delta_step)
    gain_out = (best_gain if feature_contri is not None
                else best_gain - min_gain_shift)
    return BestSplit(
        gain=jnp.where(best_gain > neg, gain_out, neg),
        feature=best_f.astype(jnp.int32),
        threshold=best_t.astype(jnp.int32),
        default_left=dl > 0.5,
        left_sum_g=lg, left_sum_h=lh - K_EPSILON,
        right_sum_g=rg, right_sum_h=rh - K_EPSILON,
        left_count=lc_f32.astype(jnp.int32),
        right_count=rc.astype(jnp.int32),
        left_output=leaf_output(lg, lh, *args),
        right_output=leaf_output(rg, rh, *args),
        is_cat=jnp.bool_(False),
        cat_set=jnp.zeros((1,), jnp.bool_),
    )


def _linear_side(g, h, xg, xh, xxh, l2: float, lam: float):
    """Closed-form leaf gain + model over ``f(x) = coeff*x + const``.

    Centered ridge normal equations: with ``xm = Σxh/Σh`` the
    h-weighted mean, the 2x2 system diagonalizes into the constant part
    and an independent slope part over the centered regressor —

        gain  = g^2/(h + l2)  +  xgc^2/(var + lam)
        coeff = -xgc/(var + lam),  const = -g/(h + l2) - coeff*xm

    where ``xgc = Σxg - xm*Σg`` and ``var = Σx^2h - xm*Σxh`` (the
    h-weighted variance mass).  ``lam`` is ``linear_lambda`` on the
    slope, ``l2`` stays on the (centered) intercept — the constant term
    and NaN-fallback value therefore match the constant search exactly.
    The centered form avoids the catastrophic f32 cancellation of the
    raw determinant when x barely varies inside a leaf; a
    non-positive ``var`` (constant regressor, or cancellation noise)
    falls back to the constant model — the reference's degenerate-leaf
    behaviour (linear_tree_learner.cpp singular-XTHX guard)."""
    xm = xh / h
    xgc = xg - xm * g
    var = xxh - xm * xh
    lin_ok = var > 0.0
    denom = jnp.where(lin_ok, var + lam, jnp.float32(1.0))
    coeff = jnp.where(lin_ok, -xgc / denom, jnp.float32(0.0))
    gain = g * g / (h + l2) + jnp.where(lin_ok, xgc * xgc / denom,
                                        jnp.float32(0.0))
    const = -g / (h + l2) - coeff * xm
    return gain, coeff, const


def find_best_split_linear(feat_hist: jnp.ndarray, ctx: SplitContext,
                           sum_g, sum_h, num_data,
                           l2: float, min_gain_to_split: float,
                           min_data_in_leaf: int, min_sum_hessian: float,
                           rep_vals: jnp.ndarray, linear_lambda: float,
                           feature_mask: jnp.ndarray | None = None,
                           rand_bins: jnp.ndarray | None = None):
    """Piece-wise-linear best-split search (linear_tree_mode=
    leafwise_gain): split gain is computed over leaf-local LINEAR
    models, vectorized over (feature, bin, direction) exactly like
    ``find_best_split_fast`` — same masks, same candidate order, same
    tie-breaking, same packed winner read.

    The linear moment planes Σx·g, Σx·h, Σx·x·h are NOT extra matmul
    accumulations: within one bin the (binned) regressor is a per-bin
    constant, so each moment plane is the existing G/H histogram scaled
    by the per-(feature, bin) representative value ``rep_vals`` (F, BF)
    (see ops/histogram.py:linear_moment_planes — strictly cheaper than
    accumulating extra one-hot columns, and the subtraction trick holds
    automatically).  ``rep_vals`` must be 0 at the NaN bin and at the
    MISSING_ZERO default bin (the rows routed by ``default_left``), so
    both scan directions share ONE set of moment prefix sums: missing
    rows contribute zero moment mass wherever they land.

    Gain per side is the centered closed form of ``_linear_side``.

    The gain shift is the searched leaf's OWN fitted model gain, not
    the constant parent gain: the leaf already predicts with its best
    whole-leaf single-feature model (fitted here from the per-feature
    moment TOTALS — the last prefix-sum entry, so it is free), and a
    split replaces that model with two children fitted on the split
    feature only.  Shifting by the constant gain overstates every
    candidate by (self model gain - constant gain) and measurably
    picks splits that LOSE realized training loss — the children drop
    the slope the parent's model carried.  With the self-model shift,
    ``gain`` is the exact realized surrogate improvement of the split
    (f32 histogram noise aside).

    ``l1`` / ``max_delta_step`` / monotone / CEGB are ineligible for
    this mode (the caller gates and falls back to refit).  Returns
    ``BestSplitLinear`` — the leaf's own (const, coeff, feature) model
    rides along for the tree builder to record."""
    F, BF, _ = feat_hist.shape
    G = feat_hist[..., 0]
    H = feat_hist[..., 1]
    sum_h_tot = sum_h + 2 * K_EPSILON
    num_data = num_data.astype(jnp.float32) if hasattr(num_data, "astype") \
        else jnp.float32(num_data)
    cnt_factor = num_data / sum_h_tot

    bins = jax.lax.broadcasted_iota(jnp.int32, (F, BF), 1)
    nb = ctx.num_bin[:, None]
    in_range = bins < nb
    missing = ctx.missing_type[:, None]
    dflt = ctx.default_bin[:, None]
    is_zero_miss = missing == MISSING_ZERO
    is_nan_miss = missing == MISSING_NAN
    two_scan = (nb > 2) & (missing != MISSING_NONE)
    cnt_bin = jnp.floor(H * cnt_factor + 0.5) * in_range      # f32, exact

    mask_f = in_range & ~(is_zero_miss & (bins == dflt))
    bmax = nb - 1 - (is_nan_miss & two_scan).astype(jnp.int32)
    mask_r = (in_range & ~(two_scan & is_zero_miss & (bins == dflt)) &
              (bins <= bmax))

    z = jnp.float32(0.0)
    rep = jnp.where(in_range, rep_vals.astype(jnp.float32), z)
    XG = rep * G
    XH = rep * H
    XXH = rep * XH
    stacked = jnp.stack([
        jnp.where(mask_f, G, z), jnp.where(mask_f, H, z),
        jnp.where(mask_f, cnt_bin, z),
        jnp.where(mask_r, G, z), jnp.where(mask_r, H, z),
        jnp.where(mask_r, cnt_bin, z),
        XG, XH, XXH])                                         # (9, F, BF)
    if jax.default_backend() == "tpu":
        tri = (jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 0) <=
               jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 1)
               ).astype(jnp.float32)
        cs = jax.lax.dot_general(
            stacked, tri, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (9, F, BF)
    else:
        cs = jnp.cumsum(stacked, axis=2)                      # (9, F, BF)

    left_g_f = cs[0]
    left_h_f = cs[1] + K_EPSILON
    left_c_f = cs[2]
    right_g_f = sum_g - left_g_f
    right_h_f = sum_h_tot - left_h_f
    right_c_f = num_data - left_c_f

    right_g_r = cs[3, :, -1:] - cs[3]
    right_h_r = cs[4, :, -1:] - cs[4] + K_EPSILON
    right_c_r = cs[5, :, -1:] - cs[5]
    left_g_r = sum_g - right_g_r
    left_h_r = sum_h_tot - right_h_r
    left_c_r = num_data - right_c_r

    # moment prefix sums are direction-agnostic (missing rows carry
    # zero moment mass): left = inclusive prefix, right = total - left
    lxg, lxh, lxxh = cs[6], cs[7], cs[8]
    rxg = cs[6, :, -1:] - lxg
    rxh = cs[7, :, -1:] - lxh
    rxxh = cs[8, :, -1:] - lxxh

    lam = jnp.float32(linear_lambda)
    lgain_f, _, _ = _linear_side(left_g_f, left_h_f,
                                 lxg, lxh, lxxh, l2, lam)
    rgain_f, _, _ = _linear_side(right_g_f, right_h_f,
                                 rxg, rxh, rxxh, l2, lam)
    lgain_r, _, _ = _linear_side(left_g_r, left_h_r,
                                 lxg, lxh, lxxh, l2, lam)
    rgain_r, _, _ = _linear_side(right_g_r, right_h_r,
                                 rxg, rxh, rxxh, l2, lam)
    gain_f = lgain_f + rgain_f
    gain_r = lgain_r + rgain_r

    # the leaf's OWN model: best whole-leaf single-feature fit over the
    # moment totals (feature_mask-restricted, like the candidates — the
    # sampled-out features stay invisible to this node).  Degenerate
    # features (trivial/categorical rep rows are all-zero, or var<=0)
    # fall back inside _linear_side to the constant model, so the
    # argmax always yields a usable (coeff, const) pair.
    sf_gain, sf_coeff, sf_const = _linear_side(
        sum_g, sum_h_tot, cs[6, :, -1], cs[7, :, -1], cs[8, :, -1],
        l2, lam)
    sf_cand = sf_gain if feature_mask is None else \
        jnp.where(feature_mask, sf_gain, jnp.float32(K_MIN_SCORE))
    sf_j = jnp.argmax(sf_cand).astype(jnp.int32)
    self_gain = sf_gain[sf_j]
    self_coeff = sf_coeff[sf_j]
    self_const = sf_const[sf_j]
    self_feature = ctx.feature_index[sf_j]

    # shift: the leaf's own model gain (see docstring) — a split must
    # beat the model the leaf already predicts with
    min_gain_shift = self_gain + min_gain_to_split
    mdl = jnp.float32(min_data_in_leaf)

    def common_valid(lc, rc, lh, rh):
        return ((lc >= mdl) & (rc >= mdl) &
                (lh >= min_sum_hessian) & (rh >= min_sum_hessian))

    valid_f = (two_scan & in_range & (bins <= nb - 2) &
               ~(is_zero_miss & (bins == dflt)) &
               common_valid(left_c_f, right_c_f, left_h_f, right_h_f) &
               (gain_f > min_gain_shift))
    valid_r = (in_range & (bins <= bmax - 1) &
               ~(two_scan & is_zero_miss & (bins == dflt - 1)) &
               common_valid(left_c_r, right_c_r, left_h_r, right_h_r) &
               (gain_r > min_gain_shift))
    if feature_mask is not None:
        valid_f &= feature_mask[:, None]
        valid_r &= feature_mask[:, None]
    if rand_bins is not None:
        at_rand = bins == rand_bins[:, None]
        valid_f &= at_rand
        valid_r &= at_rand

    neg = jnp.float32(K_MIN_SCORE)
    cand_f = jnp.where(valid_f, gain_f, neg)
    cand_r = jnp.where(valid_r, gain_r, neg)
    gains = jnp.concatenate([cand_r[:, ::-1], cand_f], axis=1)
    dl_r = jnp.broadcast_to((two_scan | ~is_nan_miss).astype(jnp.float32),
                            (F, BF))
    stats = jnp.stack([
        jnp.concatenate([left_g_r[:, ::-1], left_g_f], axis=1),
        jnp.concatenate([left_h_r[:, ::-1], left_h_f], axis=1),
        jnp.concatenate([left_c_r[:, ::-1], left_c_f], axis=1),
        jnp.concatenate([dl_r, jnp.zeros((F, BF), jnp.float32)], axis=1),
    ]).reshape(4, F * 2 * BF)

    flat = gains.reshape(F * 2 * BF)
    widx = jnp.argmax(flat).astype(jnp.int32)
    best_gain = flat[widx]
    picked = jax.lax.dynamic_slice(stats, (0, widx), (4, 1))[:, 0]
    lg, lh, lc_f32, dl = picked[0], picked[1], picked[2], picked[3]

    per_f = 2 * BF
    best_f = widx // per_f
    r = widx - best_f * per_f
    best_t = jnp.where(r < BF, BF - 1 - r, r - BF)

    rg = sum_g - lg
    rh = sum_h_tot - lh
    rc = num_data - lc_f32
    invalid = best_gain <= neg
    return BestSplitLinear(
        gain=jnp.where(invalid, neg, best_gain - min_gain_shift),
        feature=best_f.astype(jnp.int32),
        threshold=best_t.astype(jnp.int32),
        default_left=dl > 0.5,
        left_sum_g=lg, left_sum_h=lh - K_EPSILON,
        right_sum_g=rg, right_sum_h=rh - K_EPSILON,
        left_count=lc_f32.astype(jnp.int32),
        right_count=rc.astype(jnp.int32),
        left_output=leaf_output(lg, lh, 0.0, l2, 0.0),
        right_output=leaf_output(rg, rh, 0.0, l2, 0.0),
        is_cat=jnp.bool_(False),
        cat_set=jnp.zeros((1,), jnp.bool_),
        self_const=self_const, self_coeff=self_coeff,
        self_feature=self_feature,
    )


def find_best_split(feat_hist: jnp.ndarray, ctx: SplitContext,
                    sum_g, sum_h, num_data,
                    l1: float, l2: float, max_delta_step: float,
                    min_gain_to_split: float, min_data_in_leaf: int,
                    min_sum_hessian: float,
                    feature_mask: jnp.ndarray | None = None,
                    cat_params: dict | None = None,
                    monotone: jnp.ndarray | None = None,
                    cmin=None, cmax=None, depth=None,
                    monotone_penalty: float = 0.0,
                    cegb_count_coeff: float = 0.0,
                    cegb_feature_delta: jnp.ndarray | None = None,
                    path_smooth: float = 0.0, parent_output=None,
                    with_feature_gains: bool = False,
                    rand_bins: jnp.ndarray | None = None,
                    feature_contri: jnp.ndarray | None = None):
    """Find the best numerical split for one leaf.

    Args:
      feat_hist: (F, BF, 2) per-feature histogram view (default-bin stats
        already reconstructed for bundled features).
      ctx: per-feature metadata.
      sum_g/sum_h/num_data: leaf aggregates (sum_h WITHOUT the 2*eps pad; the
        pad is applied here like FindBestThreshold, feature_histogram.hpp:165).
      feature_mask: optional (F,) bool — features allowed at this node
        (feature_fraction / interaction constraints).
      monotone: optional (F,) int32 per-feature monotone direction (+1/-1/0);
        when given, basic-mode monotone constraints are active (reference:
        monotone_constraints.hpp BasicLeafConstraints + the USE_MC arms of
        feature_histogram.hpp GetSplitGains): child outputs are clipped to
        the leaf's [cmin, cmax] bounds, candidates violating the direction
        are rejected, and `monotone_penalty` shrinks gains of splits on
        monotone features by depth (serial_tree_learner.cpp:988).
      with_feature_gains: also return the (F,) per-feature best gains
        (absolute, K_MIN_SCORE where invalid) — used by the voting-parallel
        learner's local vote (voting_parallel_tree_learner.cpp).
    """
    F, BF, _ = feat_hist.shape
    G = feat_hist[..., 0]
    H = feat_hist[..., 1]
    sum_h_tot = sum_h + 2 * K_EPSILON
    num_data = num_data.astype(jnp.float32) if hasattr(num_data, "astype") else jnp.float32(num_data)
    cnt_factor = num_data / sum_h_tot

    bins = jax.lax.broadcasted_iota(jnp.int32, (F, BF), 1)
    nb = ctx.num_bin[:, None]
    in_range = bins < nb
    missing = ctx.missing_type[:, None]
    dflt = ctx.default_bin[:, None]
    is_zero_miss = missing == MISSING_ZERO
    is_nan_miss = missing == MISSING_NAN
    two_scan = (ctx.num_bin[:, None] > 2) & (missing != MISSING_NONE)

    # per-bin estimated counts (reference rounds per bin: Common::RoundInt)
    cnt_bin = jnp.floor(H * cnt_factor + 0.5).astype(jnp.int32) * in_range

    # --- forward scan (missing goes right) ---
    skip_fwd = is_zero_miss & (bins == dflt)
    Gf = jnp.where(in_range & ~skip_fwd, G, 0.0)
    Hf = jnp.where(in_range & ~skip_fwd, H, 0.0)
    Cf = jnp.where(in_range & ~skip_fwd, cnt_bin, 0)
    left_g_f = jnp.cumsum(Gf, axis=1)
    left_h_f = jnp.cumsum(Hf, axis=1) + K_EPSILON
    left_c_f = jnp.cumsum(Cf, axis=1)
    right_g_f = sum_g - left_g_f
    right_h_f = sum_h_tot - left_h_f
    right_c_f = num_data.astype(jnp.int32) - left_c_f

    # --- reverse scan (missing goes left) ---
    # right side accumulates bins (t, bmax]; bmax excludes the NaN bin.
    # The single-scan fallback (num_bin<=2 or MissingType::None,
    # feature_histogram.hpp:421-451) neither skips the default bin nor
    # excludes the NaN bin, hence the `two_scan` factors.
    bmax = nb - 1 - (is_nan_miss & two_scan).astype(jnp.int32)
    skip_rev = two_scan & is_zero_miss & (bins == dflt)
    mask_rev = in_range & ~skip_rev & (bins <= bmax)
    Gr = jnp.where(mask_rev, G, 0.0)
    Hr = jnp.where(mask_rev, H, 0.0)
    Cr = jnp.where(mask_rev, cnt_bin, 0)
    cum_g_r = jnp.cumsum(Gr, axis=1)
    cum_h_r = jnp.cumsum(Hr, axis=1)
    cum_c_r = jnp.cumsum(Cr, axis=1)
    tot_g_r = cum_g_r[:, -1:]
    tot_h_r = cum_h_r[:, -1:]
    tot_c_r = cum_c_r[:, -1:]
    right_g_r = tot_g_r - cum_g_r
    right_h_r = tot_h_r - cum_h_r + K_EPSILON
    right_c_r = tot_c_r - cum_c_r
    left_g_r = sum_g - right_g_r
    left_h_r = sum_h_tot - right_h_r
    left_c_r = num_data.astype(jnp.int32) - right_c_r

    use_mc = monotone is not None
    use_smooth = path_smooth > 0.0
    # advanced monotone mode passes PER-SIDE, per-(feature, threshold)
    # bound arrays ((cmin_left, cmin_right) tuples of (F, BF)); the
    # intermediate/basic modes pass scalars shared by both children
    # (monotone_constraints.hpp:858 AdvancedLeafConstraints vs :488)
    if isinstance(cmin, tuple):
        cmin_l, cmin_r = cmin
        cmax_l, cmax_r = cmax
        # the parent's own (whole-box) bounds are the loosest per-side
        # bounds: min over thresholds of each side's bound envelope
        cmin_p = jnp.minimum(jnp.min(cmin_l), jnp.min(cmin_r))
        cmax_p = jnp.maximum(jnp.max(cmax_l), jnp.max(cmax_r))
    else:
        cmin_l = cmin_r = cmin
        cmax_l = cmax_r = cmax
        cmin_p, cmax_p = cmin, cmax
    if use_smooth:
        # reference: USE_SMOOTHING arm of FindBestThresholdSequentially —
        # gain shift is evaluated at the leaf's CURRENT output
        gain_shift = _leaf_gain_given_output(sum_g, sum_h_tot, l1, l2,
                                             parent_output)
    elif use_mc:
        parent_out_est = jnp.clip(
            leaf_output(sum_g, sum_h_tot, l1, l2, max_delta_step),
            cmin_p, cmax_p)
        gain_shift = _leaf_gain_given_output(sum_g, sum_h_tot, l1, l2,
                                             parent_out_est)
    else:
        gain_shift = leaf_gain(sum_g, sum_h_tot, l1, l2, max_delta_step)
    min_gain_shift = gain_shift + min_gain_to_split

    def child_output(g, h, c, side):
        out = leaf_output(g, h, l1, l2, max_delta_step)
        if use_smooth:
            # reference: CalculateSplittedLeafOutput smoothing arm
            # (feature_histogram.hpp:717): shrink toward the parent output
            # proportionally to n/path_smooth
            f = c.astype(jnp.float32) / path_smooth
            out = out * f / (f + 1.0) + parent_output / (f + 1.0)
        if use_mc:
            out = jnp.clip(out, cmin_l if side == "l" else cmin_r,
                           cmax_l if side == "l" else cmax_r)
        return out

    def side_gain(gl, hl, gr, hr, cl, cr):
        if not (use_mc or use_smooth):
            return (leaf_gain(gl, hl, l1, l2, max_delta_step) +
                    leaf_gain(gr, hr, l1, l2, max_delta_step))
        lo = child_output(gl, hl, cl, "l")
        ro = child_output(gr, hr, cr, "r")
        g = (_leaf_gain_given_output(gl, hl, l1, l2, lo) +
             _leaf_gain_given_output(gr, hr, l1, l2, ro))
        if use_mc:
            mono = monotone[:, None]
            bad = ((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro))
            g = jnp.where(bad, K_MIN_SCORE, g)
        return g

    gain_f = side_gain(left_g_f, left_h_f, right_g_f, right_h_f,
                       left_c_f, right_c_f)
    gain_r = side_gain(left_g_r, left_h_r, right_g_r, right_h_r,
                       left_c_r, right_c_r)

    def common_valid(lc, rc, lh, rh):
        return ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf) &
                (lh >= min_sum_hessian) & (rh >= min_sum_hessian))

    # forward thresholds: t in [0, num_bin-2], skip t == default_bin (Zero)
    valid_f = (two_scan & in_range & (bins <= nb - 2) &
               ~(is_zero_miss & (bins == dflt)) &
               common_valid(left_c_f, right_c_f, left_h_f, right_h_f) &
               (gain_f > min_gain_shift))
    # reverse thresholds: t in [0, bmax-1], skip t == default_bin-1 (Zero)
    valid_r = (in_range & (bins <= bmax - 1) &
               ~(two_scan & is_zero_miss & (bins == dflt - 1)) &
               common_valid(left_c_r, right_c_r, left_h_r, right_h_r) &
               (gain_r > min_gain_shift))

    numerical = ctx.is_categorical[:, None] == 0
    valid_f &= numerical
    valid_r &= numerical
    if feature_mask is not None:
        valid_f &= feature_mask[:, None]
        valid_r &= feature_mask[:, None]
    if rand_bins is not None:
        # extra_trees: each feature evaluates ONE random threshold
        # (feature_histogram.hpp USE_RAND arms)
        at_rand = bins == rand_bins[:, None]
        valid_f &= at_rand
        valid_r &= at_rand

    neg = jnp.float32(K_MIN_SCORE)
    gain_f = jnp.where(valid_f, gain_f, neg)
    gain_r = jnp.where(valid_r, gain_r, neg)

    # per-feature best, with scan-order tie-breaking
    best_t_f = jnp.argmax(gain_f, axis=1)            # first (smallest t) wins
    best_gain_f = jnp.take_along_axis(gain_f, best_t_f[:, None], axis=1)[:, 0]
    rev_flip = gain_r[:, ::-1]
    best_t_r_flip = jnp.argmax(rev_flip, axis=1)      # largest t wins ties
    best_t_r = BF - 1 - best_t_r_flip
    best_gain_r = jnp.take_along_axis(gain_r, best_t_r[:, None], axis=1)[:, 0]

    use_fwd = best_gain_f > best_gain_r              # strict: reverse wins ties
    feat_gain = jnp.where(use_fwd, best_gain_f, best_gain_r)
    feat_thresh = jnp.where(use_fwd, best_t_f, best_t_r)
    # default_left: reverse scan => True; single-scan NaN feature => False
    single_nan = (~two_scan & is_nan_miss)[:, 0]
    feat_default_left = jnp.where(use_fwd, False, True) & ~single_nan

    # ---- categorical features (exclusive with the numerical scans) ----
    cat_mask = ctx.is_categorical != 0
    if cat_params is not None:
        (gain_c, member_c, lg_c, lh_c, lc_c, l2_eff_c) = \
            find_best_split_categorical(
                feat_hist, ctx, sum_g, sum_h_tot, num_data,
                l1, l2, max_delta_step, min_gain_shift,
                min_data_in_leaf, min_sum_hessian,
                cat_params["max_cat_threshold"], cat_params["cat_l2"],
                cat_params["cat_smooth"], cat_params["max_cat_to_onehot"],
                cat_params["min_data_per_group"],
                cmin=cmin_p if use_mc else None,
                cmax=cmax_p if use_mc else None)
        if feature_mask is not None:
            gain_c = jnp.where(feature_mask, gain_c, neg)
        feat_gain = jnp.where(cat_mask, gain_c, feat_gain)
    else:
        member_c = jnp.zeros((F, BF), jnp.bool_)
        lg_c = jnp.zeros((F,))
        lh_c = jnp.zeros((F,))
        lc_c = jnp.zeros((F,), jnp.int32)
        l2_eff_c = jnp.full((F,), l2)

    if feature_contri is not None:
        # per-feature gain scaling (feature_histogram.hpp:174), applied
        # BEFORE the CEGB delta like the reference (the penalty scales
        # inside FindBestThreshold; CEGB subtracts at
        # serial_tree_learner.cpp:982)
        rel = feat_gain - min_gain_shift
        feat_gain = jnp.where(feat_gain > neg,
                              min_gain_shift + rel * feature_contri, neg)

    if cegb_count_coeff > 0.0 or cegb_feature_delta is not None:
        # CEGB: subtract the split cost from the (relative) gain
        # (reference: CostEfficientGradientBoosting::DeltaGain,
        # cost_effective_gradient_boosting.hpp; applied at
        # serial_tree_learner.cpp:982-986)
        delta = cegb_count_coeff * num_data
        if cegb_feature_delta is not None:
            delta = delta + cegb_feature_delta
        rel = feat_gain - min_gain_shift - delta
        feat_gain = jnp.where(feat_gain > neg, min_gain_shift + rel, neg)

    if use_mc and monotone_penalty > 0:
        # gain *= penalty for splits on monotone features
        # (serial_tree_learner.cpp:987-991; penalty from
        # monotone_constraints.hpp:357 as a function of leaf depth)
        d = depth.astype(jnp.float32)
        pen = jnp.where(
            monotone_penalty >= d + 1.0, K_EPSILON,
            jnp.where(jnp.float32(monotone_penalty) <= 1.0,
                      1.0 - monotone_penalty / jnp.exp2(d) + K_EPSILON,
                      1.0 - jnp.exp2(monotone_penalty - 1.0 - d) + K_EPSILON))
        rel = feat_gain - min_gain_shift
        rel = jnp.where(monotone != 0, rel * pen, rel)
        feat_gain = jnp.where(feat_gain > neg, min_gain_shift + rel, neg)

    best_f = jnp.argmax(feat_gain)                   # smallest feature wins ties
    best_gain = feat_gain[best_f]
    best_t = feat_thresh[best_f]
    fwd_sel = use_fwd[best_f]
    is_cat = cat_mask[best_f]

    lg_n = jnp.where(fwd_sel, left_g_f[best_f, best_t], left_g_r[best_f, best_t])
    lh_n = jnp.where(fwd_sel, left_h_f[best_f, best_t], left_h_r[best_f, best_t])
    lc_n = jnp.where(fwd_sel, left_c_f[best_f, best_t], left_c_r[best_f, best_t])
    lg = jnp.where(is_cat, lg_c[best_f], lg_n)
    lh = jnp.where(is_cat, lh_c[best_f], lh_n)
    lc = jnp.where(is_cat, lc_c[best_f], lc_n)
    l2_out = jnp.where(is_cat, l2_eff_c[best_f], l2)
    rg = sum_g - lg
    rh = sum_h_tot - lh
    rc = num_data.astype(jnp.int32) - lc

    lout_best = leaf_output(lg, lh, l1, l2_out, max_delta_step)
    rout_best = leaf_output(rg, rh, l1, l2_out, max_delta_step)
    if use_smooth:
        fl = lc.astype(jnp.float32) / path_smooth
        fr = rc.astype(jnp.float32) / path_smooth
        lout_best = lout_best * fl / (fl + 1.0) + parent_output / (fl + 1.0)
        rout_best = rout_best * fr / (fr + 1.0) + parent_output / (fr + 1.0)
    if use_mc:
        def _at_best(b, parent):
            # per-threshold (F, BF) bound arrays (advanced mode) index at
            # the chosen split; a categorical winner's best_t is leftover
            # from the masked numerical scan, so categorical splits use
            # the whole-box parent bound instead.  Scalars pass through.
            if getattr(b, "ndim", 0) != 2:
                return b
            return jnp.where(is_cat, parent, b[best_f, best_t])
        lout_best = jnp.clip(lout_best, _at_best(cmin_l, cmin_p),
                             _at_best(cmax_l, cmax_p))
        rout_best = jnp.clip(rout_best, _at_best(cmin_r, cmin_p),
                             _at_best(cmax_r, cmax_p))

    best = BestSplit(
        gain=jnp.where(best_gain > neg, best_gain - min_gain_shift, neg),
        feature=best_f.astype(jnp.int32),
        threshold=jnp.where(is_cat, 0, best_t).astype(jnp.int32),
        default_left=jnp.where(is_cat, False, feat_default_left[best_f]),
        left_sum_g=lg, left_sum_h=lh - K_EPSILON,
        right_sum_g=rg, right_sum_h=rh - K_EPSILON,
        left_count=lc.astype(jnp.int32), right_count=rc.astype(jnp.int32),
        left_output=lout_best,
        right_output=rout_best,
        is_cat=is_cat,
        cat_set=member_c[best_f],
    )
    if with_feature_gains:
        return best, feat_gain
    return best


# ---------------------------------------------------------------------------
# Frontier-batched growth: top-K leaf selection (models/learner.py)
# ---------------------------------------------------------------------------
def oracle_next_pick(gains, oracle_slots, avail):
    """The K=1 oracle's next-leaf election over a frontier of candidate
    items: maximum gain, ties broken by the SMALLEST oracle leaf slot —
    exactly the first-max semantics of ``jnp.argmax`` over the oracle's
    leaf-indexed gain row (the serial learner's selection at
    models/learner.py ``body``).  Vectorized like the (feature, bin)
    gain argmax above: one masked max + one masked min + one argmax.

    Args: gains (I,) f32; oracle_slots (I,) i32 (valid where avail);
    avail (I,) bool.  Returns (item, gain) of the elected candidate
    (item is arbitrary-but-deterministic when nothing is available:
    gains must be -inf there so the caller's gain check gates it).
    """
    masked = jnp.where(avail, gains, K_MIN_SCORE)
    gmax = jnp.max(masked)
    tie = avail & (masked == gmax)
    big = jnp.int32(2 ** 30)
    slot = jnp.min(jnp.where(tie, oracle_slots, big))
    item = jnp.argmax(tie & (oracle_slots == slot)).astype(jnp.int32)
    return item, gmax


def frontier_topk(scores, required, k):
    """Select the step's split batch: the ``required`` item (the oracle's
    guaranteed-next split) plus the top-(k-1) remaining candidates by
    score.  ``scores`` must already be ``-inf`` for non-candidates.
    Returns (items (k,), ok (k,) validity mask); slot 0 is always the
    required item (the caller masks its own validity)."""
    required = jnp.asarray(required, jnp.int32)
    if k == 1:
        return required[None], jnp.ones((1,), jnp.bool_)
    rest = scores.at[required].set(K_MIN_SCORE)
    topv, topi = jax.lax.top_k(rest, k - 1)
    sel = jnp.concatenate([required[None], topi.astype(jnp.int32)])
    ok = jnp.concatenate([jnp.ones((1,), jnp.bool_), jnp.isfinite(topv)])
    return sel, ok
