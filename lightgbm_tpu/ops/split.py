"""Best-split search over histograms, vectorized across (feature, bin).

TPU-native replacement for the reference's per-feature sequential threshold
scan (src/treelearner/feature_histogram.hpp FindBestThresholdSequentially:830,
GetSplitGains:759, CalculateSplittedLeafOutput:717) and the CUDA best-split
kernels (src/treelearner/cuda/cuda_best_split_finder.cu): the forward/reverse
accumulations become masked cumulative sums over the bin axis, gains are
evaluated for every (feature, bin, direction) candidate at once on the VPU,
and the arg-max reduction reproduces the reference's scan-order tie-breaking:

  * reverse scan runs "first" (forward replaces only on strictly-greater gain),
  * within the reverse scan larger thresholds win ties,
  * within the forward scan smaller thresholds win ties,
  * across features the smaller feature index wins ties.

Missing-value handling mirrors the reference dispatch
(feature_histogram.hpp FuncForNumricalL3:272-455):
  * MissingType::Zero  -> both scans skip the default(zero) bin; zeros follow
    ``default_left`` (reverse scan => default_left=True).
  * MissingType::NaN   -> the last bin holds NaNs; the reverse scan keeps it
    out of the right side (NaN defaults left), the forward scan keeps it right.
  * MissingType::None  -> single reverse scan, no skipping.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitContext(NamedTuple):
    """Static per-feature metadata, device-resident (shapes (F,))."""
    num_bin: jnp.ndarray        # int32
    missing_type: jnp.ndarray   # int32
    default_bin: jnp.ndarray    # int32
    is_categorical: jnp.ndarray  # int32 (categorical handled separately)
    feature_index: jnp.ndarray  # int32 original feature id (for reporting)


class BestSplit(NamedTuple):
    gain: jnp.ndarray           # f32 scalar, relative gain (already minus shift)
    feature: jnp.ndarray        # int32, index into the used-feature enumeration
    threshold: jnp.ndarray      # int32 bin threshold
    default_left: jnp.ndarray   # bool
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    left_count: jnp.ndarray     # int32 (hessian-estimated, like the reference)
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray


def _threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(0.0, jnp.abs(s) - l1)


def leaf_output(sum_g, sum_h, l1, l2, max_delta_step):
    """reference: CalculateSplittedLeafOutput (feature_histogram.hpp:717)."""
    ret = -_threshold_l1(sum_g, l1) / (sum_h + l2)
    if max_delta_step > 0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    return ret


def _leaf_gain_given_output(sum_g, sum_h, l1, l2, out):
    sg = _threshold_l1(sum_g, l1)
    return -(2.0 * sg * out + (sum_h + l2) * out * out)


def leaf_gain(sum_g, sum_h, l1, l2, max_delta_step):
    """reference: GetLeafGain (feature_histogram.hpp:800)."""
    if max_delta_step > 0:
        out = leaf_output(sum_g, sum_h, l1, l2, max_delta_step)
        return _leaf_gain_given_output(sum_g, sum_h, l1, l2, out)
    sg = _threshold_l1(sum_g, l1)
    return sg * sg / (sum_h + l2)


def find_best_split(feat_hist: jnp.ndarray, ctx: SplitContext,
                    sum_g, sum_h, num_data,
                    l1: float, l2: float, max_delta_step: float,
                    min_gain_to_split: float, min_data_in_leaf: int,
                    min_sum_hessian: float,
                    feature_mask: jnp.ndarray | None = None) -> BestSplit:
    """Find the best numerical split for one leaf.

    Args:
      feat_hist: (F, BF, 2) per-feature histogram view (default-bin stats
        already reconstructed for bundled features).
      ctx: per-feature metadata.
      sum_g/sum_h/num_data: leaf aggregates (sum_h WITHOUT the 2*eps pad; the
        pad is applied here like FindBestThreshold, feature_histogram.hpp:165).
      feature_mask: optional (F,) bool — features allowed at this node
        (feature_fraction / interaction constraints).
    """
    F, BF, _ = feat_hist.shape
    G = feat_hist[..., 0]
    H = feat_hist[..., 1]
    sum_h_tot = sum_h + 2 * K_EPSILON
    num_data = num_data.astype(jnp.float32) if hasattr(num_data, "astype") else jnp.float32(num_data)
    cnt_factor = num_data / sum_h_tot

    bins = jax.lax.broadcasted_iota(jnp.int32, (F, BF), 1)
    nb = ctx.num_bin[:, None]
    in_range = bins < nb
    missing = ctx.missing_type[:, None]
    dflt = ctx.default_bin[:, None]
    is_zero_miss = missing == MISSING_ZERO
    is_nan_miss = missing == MISSING_NAN
    two_scan = (ctx.num_bin[:, None] > 2) & (missing != MISSING_NONE)

    # per-bin estimated counts (reference rounds per bin: Common::RoundInt)
    cnt_bin = jnp.floor(H * cnt_factor + 0.5).astype(jnp.int32) * in_range

    # --- forward scan (missing goes right) ---
    skip_fwd = is_zero_miss & (bins == dflt)
    Gf = jnp.where(in_range & ~skip_fwd, G, 0.0)
    Hf = jnp.where(in_range & ~skip_fwd, H, 0.0)
    Cf = jnp.where(in_range & ~skip_fwd, cnt_bin, 0)
    left_g_f = jnp.cumsum(Gf, axis=1)
    left_h_f = jnp.cumsum(Hf, axis=1) + K_EPSILON
    left_c_f = jnp.cumsum(Cf, axis=1)
    right_g_f = sum_g - left_g_f
    right_h_f = sum_h_tot - left_h_f
    right_c_f = num_data.astype(jnp.int32) - left_c_f

    # --- reverse scan (missing goes left) ---
    # right side accumulates bins (t, bmax]; bmax excludes the NaN bin.
    # The single-scan fallback (num_bin<=2 or MissingType::None,
    # feature_histogram.hpp:421-451) neither skips the default bin nor
    # excludes the NaN bin, hence the `two_scan` factors.
    bmax = nb - 1 - (is_nan_miss & two_scan).astype(jnp.int32)
    skip_rev = two_scan & is_zero_miss & (bins == dflt)
    mask_rev = in_range & ~skip_rev & (bins <= bmax)
    Gr = jnp.where(mask_rev, G, 0.0)
    Hr = jnp.where(mask_rev, H, 0.0)
    Cr = jnp.where(mask_rev, cnt_bin, 0)
    cum_g_r = jnp.cumsum(Gr, axis=1)
    cum_h_r = jnp.cumsum(Hr, axis=1)
    cum_c_r = jnp.cumsum(Cr, axis=1)
    tot_g_r = cum_g_r[:, -1:]
    tot_h_r = cum_h_r[:, -1:]
    tot_c_r = cum_c_r[:, -1:]
    right_g_r = tot_g_r - cum_g_r
    right_h_r = tot_h_r - cum_h_r + K_EPSILON
    right_c_r = tot_c_r - cum_c_r
    left_g_r = sum_g - right_g_r
    left_h_r = sum_h_tot - right_h_r
    left_c_r = num_data.astype(jnp.int32) - right_c_r

    gain_shift = leaf_gain(sum_g, sum_h_tot, l1, l2, max_delta_step)
    min_gain_shift = gain_shift + min_gain_to_split

    def side_gain(gl, hl, gr, hr):
        return (leaf_gain(gl, hl, l1, l2, max_delta_step) +
                leaf_gain(gr, hr, l1, l2, max_delta_step))

    gain_f = side_gain(left_g_f, left_h_f, right_g_f, right_h_f)
    gain_r = side_gain(left_g_r, left_h_r, right_g_r, right_h_r)

    def common_valid(lc, rc, lh, rh):
        return ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf) &
                (lh >= min_sum_hessian) & (rh >= min_sum_hessian))

    # forward thresholds: t in [0, num_bin-2], skip t == default_bin (Zero)
    valid_f = (two_scan & in_range & (bins <= nb - 2) &
               ~(is_zero_miss & (bins == dflt)) &
               common_valid(left_c_f, right_c_f, left_h_f, right_h_f) &
               (gain_f > min_gain_shift))
    # reverse thresholds: t in [0, bmax-1], skip t == default_bin-1 (Zero)
    valid_r = (in_range & (bins <= bmax - 1) &
               ~(two_scan & is_zero_miss & (bins == dflt - 1)) &
               common_valid(left_c_r, right_c_r, left_h_r, right_h_r) &
               (gain_r > min_gain_shift))

    numerical = ctx.is_categorical[:, None] == 0
    valid_f &= numerical
    valid_r &= numerical
    if feature_mask is not None:
        valid_f &= feature_mask[:, None]
        valid_r &= feature_mask[:, None]

    neg = jnp.float32(K_MIN_SCORE)
    gain_f = jnp.where(valid_f, gain_f, neg)
    gain_r = jnp.where(valid_r, gain_r, neg)

    # per-feature best, with scan-order tie-breaking
    best_t_f = jnp.argmax(gain_f, axis=1)            # first (smallest t) wins
    best_gain_f = jnp.take_along_axis(gain_f, best_t_f[:, None], axis=1)[:, 0]
    rev_flip = gain_r[:, ::-1]
    best_t_r_flip = jnp.argmax(rev_flip, axis=1)      # largest t wins ties
    best_t_r = BF - 1 - best_t_r_flip
    best_gain_r = jnp.take_along_axis(gain_r, best_t_r[:, None], axis=1)[:, 0]

    use_fwd = best_gain_f > best_gain_r              # strict: reverse wins ties
    feat_gain = jnp.where(use_fwd, best_gain_f, best_gain_r)
    feat_thresh = jnp.where(use_fwd, best_t_f, best_t_r)
    # default_left: reverse scan => True; single-scan NaN feature => False
    single_nan = (~two_scan & is_nan_miss)[:, 0]
    feat_default_left = jnp.where(use_fwd, False, True) & ~single_nan

    best_f = jnp.argmax(feat_gain)                   # smallest feature wins ties
    best_gain = feat_gain[best_f]
    best_t = feat_thresh[best_f]
    fwd_sel = use_fwd[best_f]

    lg = jnp.where(fwd_sel, left_g_f[best_f, best_t], left_g_r[best_f, best_t])
    lh = jnp.where(fwd_sel, left_h_f[best_f, best_t], left_h_r[best_f, best_t])
    lc = jnp.where(fwd_sel, left_c_f[best_f, best_t], left_c_r[best_f, best_t])
    rg = sum_g - lg
    rh = sum_h_tot - lh
    rc = num_data.astype(jnp.int32) - lc

    return BestSplit(
        gain=jnp.where(best_gain > neg, best_gain - min_gain_shift, neg),
        feature=best_f.astype(jnp.int32),
        threshold=best_t.astype(jnp.int32),
        default_left=feat_default_left[best_f],
        left_sum_g=lg, left_sum_h=lh - K_EPSILON,
        right_sum_g=rg, right_sum_h=rh - K_EPSILON,
        left_count=lc.astype(jnp.int32), right_count=rc.astype(jnp.int32),
        left_output=leaf_output(lg, lh, l1, l2, max_delta_step),
        right_output=leaf_output(rg, rh, l1, l2, max_delta_step),
    )
