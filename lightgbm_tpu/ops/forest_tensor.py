"""Forest-as-tensor inference: layered dense traversal kernels.

``ops/predict.py`` walks the packed forest with a per-depth stacked
``while_loop`` — correct everywhere, but the loop's trip count is
data-dependent (``jnp.any(c >= 0)``), so every level pays the loop
plumbing and the lowered program keeps a ``while`` whose body XLA
cannot pipeline across levels.  The Booster accelerator paper
(arXiv:2011.02022) shows GBDT inference wants a *dataflow* layout of
dense per-level ops, and the GPU tree-boosting playbook
(arXiv:1706.08359) batches all (row, tree) pairs into wide vector ops.
This module is that reformulation for the serving hot path:

* **Layered traversal** — the maximum root-to-leaf depth ``D`` is a
  *pack-time host constant* (``tree_depths``), so traversal is ``D``
  statically-unrolled level steps: each level is ONE gather of the
  per-node planes for every (row, tree) pair plus one vectorized
  compare, no data-dependent ``while_loop`` anywhere in the lowered
  program (pinned by the ``predict.layered`` jaxlint tier-B budget).
  Rows that reach their leaf early hold a negative ~leaf code and pass
  through the remaining levels unchanged, exactly like the loop path —
  the layered leaves are INTEGER-identical to the loop oracle's, and
  the f32 accumulation uses the oracle's reduction order, so raw
  scores are bit-identical.
* **Quantized node planes** — serving inputs are already binned
  integers, so the per-node scalars pack into the narrowest planes
  that hold them: one u8 flags plane (missing type, default-left,
  bundled, categorical), one u16 bin plane (column, bin start, bin
  count, default bin, threshold) and one i16/i32 child plane.  Each
  level gathers three small typed planes instead of one wide i32
  stack — 2-4x less gather traffic — and every compare is still
  integer-exact (values promote to i32 *after* the gather).
* **Multi-forest batched execution** — ``stack_forests`` pads N small
  forests into one (forest, tree, node) tensor and
  ``predict_leaf_layered_forests`` traverses all of them over
  per-forest row blocks in ONE compiled program, so a tenant cohort's
  same-bucket requests cost a single dispatch
  (``serving/registry.py`` cohort packs).

The loop path (``predict_leaf_binned``) stays the any-shape oracle;
the serving engine picks a kernel per the ``predict_kernel`` config
knob (``auto | layered | loop``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import predict as predict_ops

# beyond this depth the unrolled program stops paying for itself (and
# compile time grows linearly); the engine falls back to the loop
# oracle.  Depth ~ log2(num_leaves) for balanced trees: 64 covers every
# realistic serving forest including fully degenerate 64-leaf chains.
MAX_UNROLL_DEPTH = 64

# flags plane rows (u8)
_F_BUNDLED, _F_MISSING, _F_DLEFT, _F_CAT = 0, 1, 2, 3
# bin plane rows (u16, or i32 fallback when any value overflows u16)
_B_COL, _B_START, _B_NUMBIN, _B_DEFBIN, _B_THRESH = 0, 1, 2, 3, 4


def tree_depths(left: np.ndarray, right: np.ndarray,
                num_nodes: np.ndarray) -> np.ndarray:
    """(T,) max root-to-leaf depth (= level steps to settle every row)
    per tree, from host (T, n_max) child arrays.  An empty tree (zero
    nodes) needs 0 steps; a single-split tree needs 1."""
    left = np.asarray(left)
    right = np.asarray(right)
    num_nodes = np.asarray(num_nodes).reshape(-1)
    T = left.shape[0] if left.ndim == 2 else 1
    left = left.reshape(T, -1)
    right = right.reshape(T, -1)
    out = np.zeros(T, np.int32)
    for t in range(T):
        nn = int(num_nodes[t])
        if nn <= 0:
            continue
        depth = np.zeros(nn, np.int32)
        frontier = [0]
        d = 0
        while frontier:
            nxt = []
            for nid in frontier:
                depth[nid] = d
                for c in (int(left[t, nid]), int(right[t, nid])):
                    if 0 <= c < nn:
                        nxt.append(c)
            frontier = nxt
            d += 1
        # a row settles after traversing every internal node on its
        # path: deepest internal node depth + 1 steps
        out[t] = int(depth.max()) + 1
    return out


def pack_layered(node_host: Dict[str, np.ndarray]) -> Optional[Dict[str, Any]]:
    """Quantized layered planes from HOST-stacked node arrays.

    ``node_host`` holds the (T, n_max) arrays of
    ``learner.node_arrays_for_predict`` stacked over trees (plus
    ``num_nodes`` (T,) and optionally ``is_cat``/``cat_set``).
    Returns a device pack ``{flags8, bins, kids, num_nodes, cat_set?,
    max_depth}`` or None when the forest cannot take the layered path
    (values overflow the plane dtypes, or depth exceeds the unroll
    ceiling)."""
    num_nodes = np.asarray(node_host["num_nodes"], np.int32).reshape(-1)
    left = np.asarray(node_host["left"], np.int32)
    right = np.asarray(node_host["right"], np.int32)
    if left.ndim == 1:                       # single tree: add T axis
        left, right = left[None], right[None]
    depths = tree_depths(left, right, num_nodes)
    max_depth = int(depths.max()) if depths.size else 0
    if max_depth > MAX_UNROLL_DEPTH:
        return None
    T, n_max = left.shape

    def a2(name):
        a = np.asarray(node_host[name], np.int64)
        return a.reshape(T, n_max)

    col = a2("col")
    bin_start = a2("bin_start")
    num_bin = a2("num_bin")
    default_bin = a2("default_bin")
    threshold = a2("threshold")
    bins = np.stack([col, bin_start, num_bin, default_bin, threshold])
    if bins.min() < 0:
        return None
    # u16 quantized bin plane when every bin-space value fits; the i32
    # fallback keeps the layered shape (still one plane) for exotic
    # forests rather than abandoning the dataflow layout
    bins = bins.astype(np.uint16 if bins.max() < (1 << 16) else np.int32)
    flags = np.stack([
        a2("is_bundled"),
        a2("missing_type"),
        a2("default_left"),
        (a2("is_cat") if "is_cat" in node_host
         else np.zeros((T, n_max), np.int64)),
    ])
    if flags.min() < 0 or flags.max() > 255:
        return None
    flags8 = flags.astype(np.uint8)
    kids = np.stack([left, right]).astype(np.int64)
    # children are node ids (< n_max) or ~leaf codes (>= -n_max - 1)
    kdtype = np.int16 if (kids.min() >= np.iinfo(np.int16).min
                          and kids.max() <= np.iinfo(np.int16).max) \
        else np.int32
    pack = {
        "flags8": jnp.asarray(flags8),
        "bins": jnp.asarray(bins),
        "kids": jnp.asarray(kids.astype(kdtype)),
        "num_nodes": jnp.asarray(num_nodes),
        "max_depth": max_depth,
    }
    if "cat_set" in node_host and np.asarray(
            node_host.get("is_cat", 0)).any():
        pack["cat_set"] = jnp.asarray(
            np.asarray(node_host["cat_set"]).reshape(T, n_max, -1))
    return pack


def slice_layered(pack: Dict[str, Any], start: int,
                  end: int) -> Dict[str, Any]:
    """Tree-range slice of a layered pack (the engine's per-range
    sub-packs).  ``max_depth`` stays the full-forest value: extra
    levels are settled-row no-ops, and keeping it avoids a new compile
    per sub-range depth."""
    out = dict(pack)
    out["flags8"] = pack["flags8"][:, start:end]
    out["bins"] = pack["bins"][:, start:end]
    out["kids"] = pack["kids"][:, start:end]
    out["num_nodes"] = pack["num_nodes"][start:end]
    if "cat_set" in pack:
        out["cat_set"] = pack["cat_set"][start:end]
    return out


def _gather_planes(pack: Dict[str, Any], nid: jnp.ndarray):
    """One typed gather per plane for every (tree, row) pair: (P, T, n)
    planes indexed by nid (T, n) along the node axis, promoted to i32
    AFTER the narrow gather."""
    idx = nid[None, :, :]
    flags = jnp.take_along_axis(pack["flags8"], idx, axis=2).astype(
        jnp.int32)
    bins = jnp.take_along_axis(pack["bins"], idx, axis=2).astype(
        jnp.int32)
    kids = jnp.take_along_axis(pack["kids"], idx, axis=2).astype(
        jnp.int32)
    return flags, bins, kids


def _level_step(cur: jnp.ndarray, binned_t: jnp.ndarray, g_iota,
                pack: Dict[str, Any]) -> jnp.ndarray:
    """One dense level: gather + vectorized compare over all
    (tree, row) pairs.  Semantics are EXACTLY the while-body of
    ``predict_leaf_binned`` (ops/predict.py) — integer decisions, so
    the layered leaves match the loop oracle bit-for-bit."""
    active = cur >= 0
    nid = jnp.maximum(cur, 0)
    flags, bins, kids = _gather_planes(pack, nid)
    col = bins[_B_COL]
    # per-(tree,row) feature read as a masked lane reduction over G
    # (ops/predict.py's proven-fast pattern); exactly one group
    # matches, so a max-reduce keeps the narrow row dtype
    sel = g_iota[:, None, :] == col[None, :, :]          # (G, T, n)
    gb = jnp.max(jnp.where(sel, binned_t[:, None, :], 0),
                 axis=0).astype(jnp.int32)
    nb = bins[_B_NUMBIN]
    fb_raw = gb - bins[_B_START]
    in_range = (fb_raw >= 1) & (fb_raw <= nb - 1)
    fb = jnp.where(flags[_F_BUNDLED] == 1,
                   jnp.where(in_range, fb_raw, bins[_B_DEFBIN]), gb)
    # split_decision (ops/partition.py) inlined over the planes
    missing_type = flags[_F_MISSING]
    default_bin = bins[_B_DEFBIN]
    is_missing = jnp.where(
        missing_type == 1, fb == default_bin,
        jnp.where(missing_type == 2, fb == nb - 1, False))
    goes_left = jnp.where(is_missing, flags[_F_DLEFT] == 1,
                          fb <= bins[_B_THRESH])
    if "cat_set" in pack:
        cat_rows = jnp.take_along_axis(
            pack["cat_set"], nid[:, :, None], axis=1)    # (T, n, W)
        member = jnp.take_along_axis(
            cat_rows,
            jnp.minimum(fb, cat_rows.shape[2] - 1)[:, :, None],
            axis=2)[:, :, 0]
        member = member & (fb <= nb - 1)
        goes_left = jnp.where(flags[_F_CAT] == 1, member, goes_left)
    nxt = jnp.where(goes_left, kids[0], kids[1])
    # empty trees land on leaf 0 immediately (same guard as the loop
    # path: padded cohort slots and zero-node trees must settle)
    nxt = jnp.where(pack["num_nodes"][:, None] > 0, nxt, jnp.int32(-1))
    return jnp.where(active, nxt, cur)


def predict_leaf_layered(binned: jnp.ndarray, pack: Dict[str, Any],
                         max_depth: int) -> jnp.ndarray:
    """(T, n) leaf index for every (tree, row) pair of one forest.

    ``max_depth`` is a static host int (the pack's), so the level loop
    unrolls at trace time: the lowered program has NO while loop —
    each level is a gather + compare XLA can fuse and pipeline."""
    n = binned.shape[0]
    T = pack["kids"].shape[1]
    binned_t = binned.T                                  # (G, n)
    g_iota = jax.lax.broadcasted_iota(jnp.int32, binned_t.shape, 0)
    cur = jnp.zeros((T, n), dtype=jnp.int32)
    for _ in range(max_depth):
        cur = _level_step(cur, binned_t, g_iota, pack)
    # rows of empty trees never entered a level (max_depth 0 forests):
    # they sit at node 0, which decodes as leaf 0 via the same guard
    cur = jnp.where(pack["num_nodes"][:, None] > 0, cur, jnp.int32(-1))
    return -(jnp.minimum(cur, -1) + 1)


def raw_from_leaves(deltas: jnp.ndarray, leaves: jnp.ndarray,
                    mask: jnp.ndarray) -> jnp.ndarray:
    """(n,) masked raw-score sum over trees — the EXACT reduction the
    loop path uses (models/serving.py ``_fn("raw")``), so f32 layered
    scores are bit-identical to the loop oracle's."""
    vals = jax.vmap(jnp.take)(deltas, leaves)            # (T, n)
    if deltas.dtype != jnp.float32:
        # quantized (bf16) leaf planes accumulate in f32: the cast is
        # the only precision loss, the reduction stays f32
        vals = vals.astype(jnp.float32)
    return jnp.sum(vals * mask[:, None], axis=0)


def linear_from_leaves(raw_aug: jnp.ndarray, leaves: jnp.ndarray,
                       const: jnp.ndarray, coeff: jnp.ndarray,
                       fid: jnp.ndarray, fallback: jnp.ndarray,
                       mask: jnp.ndarray) -> jnp.ndarray:
    """(n,) masked raw-score sum over a stacked PIECE-WISE-LINEAR
    forest: per-tree coefficient planes ``const`` (T, L), ``coeff`` /
    ``fid`` (T, L, J) and the NaN-fallback plane ``fallback`` (T, L),
    applied to the leaves of every (tree, row) pair via the per-tree
    FMA (ops/predict.py linear_leaf_values).  ``raw_aug`` is (n, F+1)
    with the sentinel zero column last; both traversal kernels (loop
    and layered) feed the same (T, n) ``leaves``, so the linear
    reduction is kernel-agnostic exactly like :func:`raw_from_leaves`."""
    vals = jax.vmap(
        lambda lf, c, cf, ff, fb: predict_ops.linear_leaf_values(
            raw_aug, lf, c, cf, ff, fb))(
        leaves, const, coeff, fid, fallback)             # (T, n)
    return jnp.sum(vals * mask[:, None], axis=0)


# ---------------------------------------------------------------------------
# multi-forest batched execution
# ---------------------------------------------------------------------------
def stack_forests(packs: List[Dict[str, Any]],
                  deltas: List[np.ndarray]) -> Optional[Dict[str, Any]]:
    """Pad N host-side layered packs into ONE (forest, tree, node)
    tensor family.  ``packs`` are host dicts (np arrays, same keys as
    :func:`pack_layered` output); ``deltas`` the per-forest (T_f, L_f)
    leaf-value matrices.  Padded tree slots are zero-node trees whose
    leaf 0 carries delta 0, so they are exact no-ops under any mask.
    Categorical forests are not stackable (per-forest cat-set widths
    would multiply the padding); callers fall back to per-forest
    dispatch."""
    if any("cat_set" in p for p in packs):
        return None
    Nf = len(packs)
    T_max = max(p["kids"].shape[1] for p in packs)
    n_max = max(p["kids"].shape[2] for p in packs)
    L_max = max(d.shape[1] for d in deltas)
    bins_dt = (np.int32 if any(p["bins"].dtype == np.int32 for p in packs)
               else np.uint16)
    kids_dt = (np.int32 if any(p["kids"].dtype == np.int32 for p in packs)
               else np.int16)
    flags8 = np.zeros((4, Nf, T_max, n_max), np.uint8)
    bins = np.zeros((5, Nf, T_max, n_max), bins_dt)
    kids = np.zeros((2, Nf, T_max, n_max), kids_dt)
    num_nodes = np.zeros((Nf, T_max), np.int32)
    dl = np.zeros((Nf, T_max, L_max), np.float32)
    tree_mask = np.zeros((Nf, T_max), np.float32)
    for f, (p, d) in enumerate(zip(packs, deltas)):
        T, n = p["kids"].shape[1], p["kids"].shape[2]
        flags8[:, f, :T, :n] = p["flags8"]
        bins[:, f, :T, :n] = p["bins"]
        kids[:, f, :T, :n] = p["kids"]
        num_nodes[f, :T] = p["num_nodes"]
        dl[f, :T, :d.shape[1]] = d
        tree_mask[f, :T] = 1.0
    return {
        "flags8": jnp.asarray(flags8),
        "bins": jnp.asarray(bins),
        "kids": jnp.asarray(kids),
        "num_nodes": jnp.asarray(num_nodes),
        "deltas": jnp.asarray(dl),
        "tree_mask": jnp.asarray(tree_mask),
        "max_depth": max(int(p["max_depth"]) for p in packs),
    }


def predict_raw_layered_forests(binned_f: jnp.ndarray,
                                stacked: Dict[str, Any],
                                mask: jnp.ndarray,
                                max_depth: int) -> jnp.ndarray:
    """(Nf, n) raw scores for N stacked forests over per-forest row
    blocks — ONE program, one dispatch for the whole cohort.

    ``binned_f`` is (Nf, n, G_max) with each forest's rows binned by
    its OWN mappers and zero-padded to the widest group count (padded
    columns are never referenced: real nodes' column ids stay inside
    their forest's true G).  ``mask`` is the (Nf, T_max) tree mask
    (stacked pad mask x any iteration-range mask)."""

    def one(rows, flags8, bins, kids, num_nodes, deltas, m):
        pack = {"flags8": flags8, "bins": bins, "kids": kids,
                "num_nodes": num_nodes}
        leaves = predict_leaf_layered(rows, pack, max_depth)
        return raw_from_leaves(deltas, leaves, m)

    return jax.vmap(one, in_axes=(0, 1, 1, 1, 0, 0, 0))(
        binned_f, stacked["flags8"], stacked["bins"], stacked["kids"],
        stacked["num_nodes"], stacked["deltas"], mask)
