"""Split mega-kernel: partition + BOTH children's histograms in one
Pallas program per split.

The round-5 cost model (PERF.md) pinned the remaining e2e slope on
per-row INSTRUCTION count: the partition kernel's compaction networks
are VPU-issue-bound, the smaller-child histogram hides behind them, and
the per-split fixed work (histogram dispatch, smaller/larger selection,
parent-histogram subtraction, the flat hist-state RMW pass, and the two
contextual f32[L+1, G, B, 2] state copies XLA materializes around the
parent-slot dynamic slice) is what the CUDA-band target still pays.
The GPU GBDT literature (Mitchell & Frank arXiv:1806.11248, Wen et al.
arXiv:1706.08359) lands on the same design point: fuse partition and
histogram construction into one pass over the rows while they are
resident in fast memory.

This kernel extends the proven partition program
(ops/partition_pallas.py — identical pass-1/pass-2 structure, DMA
discipline and compaction networks, built strictly from the
probe-proven Mosaic subset) with an in-VMEM accumulation of BOTH
children's histograms while each chunk's rows are already loaded for
the compaction:

  * per chunk, after the split decision, the (G, C) bin rows and the
    (1, C) grad/hess rows are reduced into a (G, 4*BH, 16) accumulator
    with the digit-decomposed one-hot matmul of ops/histogram.py
    (hi = bin >> 4 weighted masks x lo = bin & 15 one-hot, MXU f32);
  * the 4*BH weighted sublanes are (left-grad, left-hess, right-grad,
    right-hess) — both children in one matmul per group;
  * rows outside the leaf range (the 128-aligned cover's foreign edges)
    carry zero weight, so bagging/GOSS masks (zeroed gradients) and the
    quantized integer carriers flow through unchanged.

Downstream, the tree loop consumes the two children histograms
IN-REGISTER for the split search: no parent histogram read, no
subtraction trick, no (L+1)-slot histogram state in the while-loop
carry at all — the two per-split parent-hist copies are structurally
gone, not just cheaper.

Bit-exactness contract: ``both_children_hist_xla`` below is the XLA
oracle — the same chunk grid (the parent cover's aligned chunks, NOT
the children's own ranges), the same decision arithmetic and the same
``_chunk_hist_group`` math, so kernel and oracle accumulate
bit-identically.  NOTE this grid differs from the subtraction path's
(child-range chunks + parent-minus-small), so mega-mode trees are
bit-identical to the mega XLA oracle but only numerically equivalent
(different f32 summation grouping) to the subtraction-path trees.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .partition_pallas import (S_A0B, S_REM, S_CNT, S_COL, S_BSTART, S_ISB,
                               S_NB, S_DBIN, S_MTYPE, S_THR, S_DL,
                               _decide_left, _excl_prefix_rights, _cdiv,
                               payload_codecs, pltpu_roll)
from . import partition_pallas as _pp


def hist_geometry(num_bins: int):
    """(BH, Bp): high-digit cardinality and the padded bin axis of the
    digit-decomposed accumulator (bin b lives at [hi=b>>4, lo=b&15])."""
    BH = (num_bins + 15) // 16
    return BH, BH * 16


def _chunk_hist_group(bins_row, wl_g, wl_h, wr_g, wr_h, BH, iota_hi,
                      iota_lo):
    """One group's both-children histogram partial for one chunk.

    Args:
      bins_row: (1, C) i32 bin values of this group.
      wl_g/wl_h/wr_g/wr_h: (1, C) f32 child-masked grad/hess rows
        (out-of-range and out-of-bag rows already zero).
      iota_hi/iota_lo: (BH, C) / (16, C) i32 row iotas.
    Returns the (4*BH, 16) f32 partial: element [j*BH + hi, lo] is the
    sum of weight row j over rows with bin == hi*16 + lo.

    Shared verbatim by the Pallas kernel and the XLA oracle so both
    accumulate bit-identically (same shapes, same dot, same order).
    """
    hi = jax.lax.shift_right_logical(
        bins_row, jnp.broadcast_to(4, bins_row.shape))
    lo = bins_row & 15
    m_hi = hi == iota_hi                                   # (BH, C)
    oh_lo = (lo == iota_lo).astype(jnp.float32)            # (16, C)
    zero = jnp.float32(0.0)
    w4 = jnp.concatenate(
        [jnp.where(m_hi, wl_g, zero), jnp.where(m_hi, wl_h, zero),
         jnp.where(m_hi, wr_g, zero), jnp.where(m_hi, wr_h, zero)],
        axis=0)                                            # (4BH, C)
    return jax.lax.dot_general(
        w4, oh_lo, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # (4BH, 16)


def unpack_hist4(acc, num_bins: int):
    """(G, 4*BH, 16) accumulator -> four (G, Bp) planes
    (left-grad, left-hess, right-grad, right-hess), bins flattened
    row-major (b = hi*16 + lo)."""
    G = acc.shape[0]
    BH, Bp = hist_geometry(num_bins)
    h4 = acc.reshape(G, 4, Bp)
    return h4[:, 0], h4[:, 1], h4[:, 2], h4[:, 3]


def both_children_hist_xla(part_bins, part_ghi, start, cnt, col,
                           dec_scalars, *, row_chunk: int, num_bins: int,
                           num_groups: int, vary=lambda x: x, cover=None):
    """XLA oracle for the mega-kernel's histogram half: BOTH children's
    histograms of the leaf range [start, start+cnt) accumulated over the
    PARENT cover's chunk grid from the PRE-partition rows.

    Must be called before the partition moves the rows.  Returns the
    (G, 4*BH, 16) accumulator (see ``unpack_hist4``); bit-identical to
    the Pallas kernel's histogram output by construction.

    ``cover`` overrides the chunk trip count (the leaf-size-adaptive
    policy passes the cover length; 0 skips the pass at runtime).
    """
    bstart, isb, nb, dbin, mtype, thr, dl = dec_scalars
    G = num_groups
    C = row_chunk
    BH, _ = hist_geometry(num_bins)
    start = jnp.asarray(start, jnp.int32)
    a0b = jax.lax.shift_right_logical(start, 7)
    rem = start - a0b * 128
    total = rem + cnt
    n_chunks = (jnp.where(cnt > 0, _cdiv(total, C), 0) if cover is None
                else cover)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (BH, C), 0)
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, C), 0)
    col_onehot = (jax.lax.iota(jnp.int32, G) == col)[:, None]

    def body(ci, acc):
        base = a0b * 128 + ci * C
        bch = jax.lax.dynamic_slice(
            part_bins, (0, base), (part_bins.shape[0], C))[:G].astype(
                jnp.int32)
        gh = jax.lax.dynamic_slice(part_ghi, (0, base), (2, C))
        g_row = gh[0:1]
        h_row = gh[1:2]
        # split-column extraction via masked reduction (sublane-dynamic
        # slices are the slow path — PERF.md round 2)
        colv = jnp.sum(bch * col_onehot, axis=0, keepdims=True)   # (1, C)
        gl_i = _decide_left(colv, bstart, isb, nb, dbin, mtype, thr, dl)
        pos = ci * C + lane
        inside_i = ((pos >= rem) & (pos < total)).astype(jnp.int32)
        in_l = (inside_i * gl_i) != 0
        in_r = (inside_i * (1 - gl_i)) != 0
        zero = jnp.float32(0.0)
        wl_g = jnp.where(in_l, g_row, zero)
        wl_h = jnp.where(in_l, h_row, zero)
        wr_g = jnp.where(in_r, g_row, zero)
        wr_h = jnp.where(in_r, h_row, zero)
        parts = jnp.stack([
            _chunk_hist_group(bch[gi:gi + 1], wl_g, wl_h, wr_g, wr_h,
                              BH, iota_hi, iota_lo)
            for gi in range(G)])                          # (G, 4BH, 16)
        return acc + parts

    acc0 = vary(jnp.zeros((G, 4 * BH, 16), jnp.float32))
    return jax.lax.fori_loop(0, n_chunks, body, acc0)


def both_children_hist_banded(part_bins, part_ghi, start, cnt, col,
                              dec_scalars, *, policy, num_bins: int,
                              num_groups: int, vary=lambda x: x):
    """Leaf-size-adaptive mega-oracle histogram (ops/chunkpolicy.py).

    The mega grid is 128-ALIGNED (chunks start at the aligned floor of
    the leaf offset), so a band applies when the leaf's ALIGNED cover
    ``(start & 127) + cnt`` fits one chunk of that width; band widths
    share the histogram menu's exactness cap.  Dispatch is zero-trip
    fori_loops, same as the plain-path bands — exactly one variant
    executes per split."""
    from .chunkpolicy import note_variant
    sizes = policy.hist_sizes
    start_i = jnp.asarray(start, jnp.int32)
    eff = (start_i & 127) + cnt
    band = policy.band(eff, sizes)
    live = cnt > 0
    base_cover = jnp.where(
        live & (band == 0), _cdiv(eff, sizes[0]), 0)
    note_variant("mega_hist", sizes[0])
    acc = both_children_hist_xla(
        part_bins, part_ghi, start, cnt, col, dec_scalars,
        row_chunk=sizes[0], num_bins=num_bins, num_groups=num_groups,
        vary=vary, cover=base_cover)
    for i, w in enumerate(sizes[1:], 1):
        note_variant("mega_hist", w)
        trip = ((band == i) & live).astype(jnp.int32)
        acc = acc + both_children_hist_xla(
            part_bins, part_ghi, start, cnt, col, dec_scalars,
            row_chunk=w, num_bins=num_bins, num_groups=num_groups,
            vary=vary, cover=trip)
    return acc


def split_megakernel_pallas(part_bins, part_ghi, sc_packed, scalars, *,
                            row_chunk: int, num_bins: int, num_groups: int,
                            ghi_live: int = 3, pack_rowid: bool = False,
                            compact_radix: bool = False,
                            interpret: bool = False):
    """Two-way stable partition of the leaf range (scalar layout: the
    S_* constants of ops/partition_pallas.py) PLUS both children's
    histograms, in one Pallas program.

    Args match ``partition_leaf_pallas`` plus:
      num_bins / num_groups: histogram geometry (bins per group; real
        group rows of ``part_bins`` — the rest are DMA-tile padding).

    Returns (part_bins', part_ghi', sc_packed', nl, hist_acc): the first
    three aliased in place; nl an (8, 128) i32 tile with the left count
    at [0, 0]; hist_acc the (G, 4*BH, 16) f32 accumulator of
    ``unpack_hist4``.  A cnt == 0 call (trash-slot iteration) moves no
    rows and returns a zero hist_acc.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    G32, Np = part_bins.shape
    GH = part_ghi.shape[0]
    assert GH == 8 and G32 % 32 == 0, (G32, GH)
    SCR = sc_packed.shape[0]
    assert (sc_packed.shape[1] == Np and SCR % 8 == 0
            and sc_packed.dtype == jnp.int32)
    C = row_chunk
    assert C >= 256 and (C & (C - 1)) == 0 and Np % 128 == 0
    logc = C.bit_length() - 1
    G = num_groups
    assert 0 < G <= G32
    BH, _ = hist_geometry(num_bins)
    assert 3 <= ghi_live <= GH
    P, W, pack_bins, unpack_bins, make_payload, split_payload = \
        payload_codecs(G32, ghi_live, pack_rowid)
    assert P <= SCR
    # late-bound so tools/profile_partition.py's network-ablation
    # monkeypatch applies here too
    compact = _pp._compact_radix4 if compact_radix else _pp._compact

    def kernel(s_ref, pb_in, pg_in, sp_in, pb, pg, sp, nl_ref, hist_ref,
               rb, rg, rs, stgl, stgr, wb, wg, wp, exb, exg, acc, sems):
        a0b = s_ref[S_A0B]
        rem = s_ref[S_REM]
        cnt = s_ref[S_CNT]
        col = s_ref[S_COL]
        total = rem + cnt
        n_chunks = jnp.where(cnt > 0, _cdiv(total, C), 0)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
        iota_hi = jax.lax.broadcasted_iota(jnp.int32, (BH, C), 0)
        iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, C), 0)
        # split column lives at byte (col // W) of packed word (col % W)
        col_k = jax.lax.div(col, W)
        col_w = col - col_k * W
        col_sh = col_k * 8
        word_oh = (jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0) == col_w
                   ).astype(jnp.int32)

        acc[:] = jnp.zeros_like(acc)

        def start_read(ci, slot):
            pltpu.make_async_copy(
                pb_in.at[:, pl.ds(a0b * 128 + ci * C, C)],
                rb.at[slot], sems.at[slot, 0]).start()
            pltpu.make_async_copy(
                pg_in.at[:, pl.ds(a0b * 128 + ci * C, C)],
                rg.at[slot], sems.at[slot, 1]).start()

        def wait_read(slot):
            pltpu.make_async_copy(
                pb_in.at[:, pl.ds(0, C)], rb.at[slot],
                sems.at[slot, 0]).wait()
            pltpu.make_async_copy(
                pg_in.at[:, pl.ds(0, C)], rg.at[slot],
                sems.at[slot, 1]).wait()

        @pl.when(n_chunks > 0)
        def _():
            start_read(0, 0)

        def body(ci, carry):
            fill_l, fill_r, nfl, nfr, nl_cnt = carry
            slot = jax.lax.rem(ci, 2)

            @pl.when(ci + 1 < n_chunks)
            def _():
                start_read(ci + 1, 1 - slot)
            wait_read(slot)

            bins_i = rb[slot].astype(jnp.int32)               # (G32, C)
            packed = pack_bins(bins_i)                        # (W, C)
            ghi_i = jax.lax.bitcast_convert_type(
                rg[slot], jnp.int32)[0:ghi_live]
            payload = make_payload(packed, ghi_i)             # (P, C)

            # --- decision (numerical splits) ---
            word = jnp.sum(packed * word_oh, axis=0,
                           keepdims=True)                     # (1, C)
            colv = jax.lax.shift_right_logical(
                word, jnp.broadcast_to(col_sh, word.shape)) & 255
            gl_i = _decide_left(colv, s_ref[S_BSTART], s_ref[S_ISB],
                                s_ref[S_NB], s_ref[S_DBIN], s_ref[S_MTYPE],
                                s_ref[S_THR], s_ref[S_DL])

            pos = ci * C + lane                 # cover-relative position
            before_i = (pos < rem).astype(jnp.int32)
            inside_i = ((pos >= rem) & (pos < total)).astype(jnp.int32)
            left = jnp.where((before_i != 0) |
                             ((inside_i != 0) & (gl_i != 0)), 1, 0)

            # --- both-children histogram accumulation: the rows are in
            # VMEM anyway; foreign cover-edge rows carry zero weight ---
            g_row = rg[slot][0:1]
            h_row = rg[slot][1:2]
            in_l = (inside_i * gl_i) != 0
            in_r = (inside_i * (1 - gl_i)) != 0
            zero = jnp.float32(0.0)
            wl_g = jnp.where(in_l, g_row, zero)
            wl_h = jnp.where(in_l, h_row, zero)
            wr_g = jnp.where(in_r, g_row, zero)
            wr_h = jnp.where(in_r, h_row, zero)
            for gi in range(G):
                acc[gi] = acc[gi] + _chunk_hist_group(
                    bins_i[gi:gi + 1], wl_g, wl_h, wr_g, wr_h,
                    BH, iota_hi, iota_lo)

            pnr = _excl_prefix_rights(left, C)       # rights before lane
            nlc = jnp.sum(left)
            nl_cnt = nl_cnt + nlc
            nrc = C - nlc

            lcomp = compact(payload, left, pnr, C, logc)
            rcomp = compact(payload, 1 - left, lane - pnr, C, logc)

            def stage(stg, comp, fill, n_add):
                rolled = pltpu.roll(comp, fill, 1)
                m1 = (lane >= fill) & (lane < fill + n_add)
                stg[:, 0:C] = jnp.where(m1, rolled, stg[:, 0:C])
                m2 = (lane + C) < (fill + n_add)
                stg[:, C:2 * C] = jnp.where(m2, rolled, stg[:, C:2 * C])
                new_fill = fill + n_add
                flushed = (new_fill >= C).astype(jnp.int32)
                return new_fill - flushed * C, flushed

            fill_l, fl_l = stage(stgl, lcomp, fill_l, nlc)
            fill_r, fl_r = stage(stgr, rcomp, fill_r, nrc)

            # lefts: unpack and flush in place (deferred-wait DMA
            # discipline identical to partition_leaf_pallas)
            @pl.when(fl_l > 0)
            def _():
                @pl.when(nfl > 0)
                def _():
                    pltpu.make_async_copy(
                        wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
                    pltpu.make_async_copy(
                        wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()
                pk_l, gl_l = split_payload(stgl[:, 0:C])
                wb[:] = unpack_bins(pk_l).astype(jnp.uint8)
                wg[:] = jax.lax.bitcast_convert_type(
                    jnp.concatenate(
                        [gl_l,
                         jnp.zeros((GH - ghi_live, C), jnp.int32)], axis=0),
                    jnp.float32)
                pltpu.make_async_copy(
                    wb, pb.at[:, pl.ds(a0b * 128 + nfl * C, C)],
                    sems.at[0, 2]).start()
                pltpu.make_async_copy(
                    wg, pg.at[:, pl.ds(a0b * 128 + nfl * C, C)],
                    sems.at[1, 2]).start()
                stgl[:, 0:C] = stgl[:, C:2 * C]

            # rights: flush STILL PACKED to the i32 scratch
            @pl.when(fl_r > 0)
            def _():
                @pl.when(nfr > 0)
                def _():
                    pltpu.make_async_copy(
                        wp, sp.at[:, pl.ds(0, C)], sems.at[0, 3]).wait()
                wp[0:P] = stgr[:, 0:C]
                pltpu.make_async_copy(
                    wp, sp.at[:, pl.ds(a0b * 128 + nfr * C, C)],
                    sems.at[0, 3]).start()
                stgr[:, 0:C] = stgr[:, C:2 * C]

            return fill_l, fill_r, nfl + fl_l, nfr + fl_r, nl_cnt

        fill_l, fill_r, nfl, nfr, nl_cnt = jax.lax.fori_loop(
            0, n_chunks, body,
            (jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0),
             jnp.int32(0)))

        hist_ref[:] = acc[:]

        @pl.when(nfl > 0)
        def _():
            pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
            pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()

        @pl.when(nfr > 0)
        def _():
            pltpu.make_async_copy(
                wp, sp.at[:, pl.ds(0, C)], sems.at[0, 3]).wait()

        # Final partial flushes (full-window writes; garbage tails are
        # rewritten by pass 2 or never read)
        @pl.when(fill_l > 0)
        def _():
            pk_f, gl_f = split_payload(stgl[:, 0:C])
            wb[:] = unpack_bins(pk_f).astype(jnp.uint8)
            wg[:] = jax.lax.bitcast_convert_type(
                jnp.concatenate(
                    [gl_f,
                     jnp.zeros((GH - ghi_live, C), jnp.int32)], axis=0),
                jnp.float32)
            cb = pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(a0b * 128 + nfl * C, C)], sems.at[0, 2])
            cg = pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(a0b * 128 + nfl * C, C)], sems.at[1, 2])
            cb.start(); cg.start(); cb.wait(); cg.wait()

        @pl.when(fill_r > 0)
        def _():
            wp[0:P] = stgr[:, 0:C]
            cp = pltpu.make_async_copy(
                wp, sp.at[:, pl.ds(a0b * 128 + nfr * C, C)], sems.at[0, 3])
            cp.start(); cp.wait()

        nl_true = jnp.where(cnt > 0, nl_cnt - rem, 0)
        nl_ref[:] = jnp.broadcast_to(nl_true, (8, 128)).astype(jnp.int32)

        # ---- pass 2: slide staged rights into [start+nl, aligned_end)
        # (identical to partition_leaf_pallas pass 2) ----
        s_r = n_chunks * C - nl_cnt
        dst_off = rem + nl_true
        dwb = a0b + jax.lax.shift_right_logical(dst_off, 7)
        r0 = dst_off - jax.lax.shift_right_logical(dst_off, 7) * 128
        n_d = jnp.where(s_r > 0, _cdiv(r0 + s_r, C), 0)
        aligned_total = n_chunks * C

        def body2(j, _):
            slot = jax.lax.rem(j, 2)
            read_src = j * C < s_r

            @pl.when(read_src)
            def _():
                pltpu.make_async_copy(
                    sp.at[:, pl.ds(a0b * 128 + j * C, C)],
                    rs.at[slot], sems.at[slot, 0]).start()
            dlo = dst_off - r0 + j * C
            lo = jnp.where(j == 0, r0, 0)
            hi = jnp.minimum(C, aligned_total - dlo)
            need_rmw = (lo > 0) | (hi < C)

            @pl.when(need_rmw)
            def _():
                cb = pltpu.make_async_copy(
                    pb.at[:, pl.ds(dwb * 128 + j * C, C)], exb,
                    sems.at[0, 3])
                cg = pltpu.make_async_copy(
                    pg.at[:, pl.ds(dwb * 128 + j * C, C)], exg,
                    sems.at[1, 3])
                cb.start(); cg.start(); cb.wait(); cg.wait()

            @pl.when(read_src)
            def _():
                pltpu.make_async_copy(
                    sp.at[:, pl.ds(0, C)], rs.at[slot],
                    sems.at[slot, 0]).wait()

            cur_p = rs[slot][0:P]
            prv_p = rs[1 - slot][0:P]
            take_prev = lane < r0
            out_p = jnp.where(take_prev, pltpu.roll(prv_p, r0, 1),
                              pltpu.roll(cur_p, r0, 1))
            pk_2, out_gl = split_payload(out_p)
            out_b = unpack_bins(pk_2)
            valid = (lane >= lo) & (lane < hi)

            @pl.when(j > 0)
            def _():
                pltpu.make_async_copy(
                    wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
                pltpu.make_async_copy(
                    wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()
            exg_i = jax.lax.bitcast_convert_type(exg[:], jnp.int32)
            wb[:] = jnp.where(valid, out_b,
                              exb[:].astype(jnp.int32)).astype(jnp.uint8)
            wg[:] = jax.lax.bitcast_convert_type(
                jnp.concatenate(
                    [jnp.where(valid, out_gl, exg_i[0:ghi_live]),
                     exg_i[ghi_live:GH]],
                    axis=0),
                jnp.float32)
            pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(dwb * 128 + j * C, C)],
                sems.at[0, 2]).start()
            pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(dwb * 128 + j * C, C)],
                sems.at[1, 2]).start()
            return 0

        jax.lax.fori_loop(0, n_d, body2, 0)

        @pl.when(n_d > 0)
        def _():
            pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
            pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 3,
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 3 +
                  [pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        scratch_shapes=[
            pltpu.VMEM((2, G32, C), jnp.uint8),      # rb
            pltpu.VMEM((2, GH, C), jnp.float32),     # rg
            pltpu.VMEM((2, SCR, C), jnp.int32),      # rs
            pltpu.VMEM((P, 2 * C), jnp.int32),       # stgl
            pltpu.VMEM((P, 2 * C), jnp.int32),       # stgr
            pltpu.VMEM((G32, C), jnp.uint8),         # wb
            pltpu.VMEM((GH, C), jnp.float32),        # wg
            pltpu.VMEM((SCR, C), jnp.int32),         # wp
            pltpu.VMEM((G32, C), jnp.uint8),         # exb
            pltpu.VMEM((GH, C), jnp.float32),        # exg
            pltpu.VMEM((G, 4 * BH, 16), jnp.float32),  # acc
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(part_bins.shape, part_bins.dtype),
            jax.ShapeDtypeStruct(part_ghi.shape, part_ghi.dtype),
            jax.ShapeDtypeStruct(sc_packed.shape, sc_packed.dtype),
            jax.ShapeDtypeStruct((8, 128), jnp.int32),
            jax.ShapeDtypeStruct((G, 4 * BH, 16), jnp.float32),
        ],
        grid_spec=grid_spec,
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(scalars, part_bins, part_ghi, sc_packed)
    return out
