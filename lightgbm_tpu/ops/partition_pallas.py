"""Pallas TPU kernel for the leaf two-way partition.

TPU-native replacement for the reference DataPartition::Split
(src/treelearner/data_partition.hpp:118-149) and the CUDA
bitvector + AggregateBlockOffset + SplitInner pipeline
(src/treelearner/cuda/cuda_data_partition.cu:288-907), built for the
measured cost structure of this stack (see PERF.md): XLA window ops on
few-sublane shapes run at 12-16 GB/s, while Pallas aligned window DMAs
run at ~360 GB/s and an in-VMEM roll-network compaction costs ~3 us per
(16, 8192) chunk.  The XLA formulation of the same partition
(models/learner.py:_partition_leaf) is kept as the CPU / fallback path
and as the correctness oracle — both produce bit-identical layouts
(lefts forward-packed in original order, rights behind them in original
order).

Design notes (all constraints below were probed on the live toolchain):
  * Window DMAs compile only with provably 128-aligned dynamic lane
    offsets (``i * 128``) and tile-multiple sublane counts (8 for 32-bit
    types, 32 for u8).  Leaf ranges are arbitrary, so the kernel reads
    the 128-aligned cover of the range and marks the foreign edge rows:
    rows before ``start`` ride as unconditional LEFTS, rows at/after
    ``start + cnt`` as unconditional RIGHTS.  Stable compaction then
    returns them to exactly their original positions.
  * No sort / gather / cumsum lower inside Pallas TPU kernels.  Prefix
    sums are computed with strictly-lower-triangular one-hot matmuls on
    the MXU; the stable two-way compaction is a 13-step binary shift
    network built from ``pltpu.roll`` (bool rolls don't lower — all
    masks stay i32).
  * The compaction payload is PACKED: 4 u8 bin rows ride per i32 row
    (row r of the packed block holds storage rows {r, W+r, 2W+r, 3W+r},
    W = G32/4) and only the 3 live grad/hess/rowid rows of the f32
    payload are carried, so the shift network moves (W+3, C) lanes
    instead of (G32+8, C) — the network's cost is proportional to
    sublane count and dominated the unpacked kernel (~4x the data).
  * Pass 1 streams the cover once: lefts are unpacked and flushed
    forward IN PLACE from the cover base (the left write frontier
    provably trails the read frontier), rights are flushed forward
    STILL PACKED into a (16, N_pad) i32 scratch.  Pass 2 slides the
    staged rights into their final windows with a two-window
    roll-select on the packed payload, unpacking only at the final
    write and read-modify-writing only the partial edge windows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


# scalar-operand layout (prefetched i32 vector)
S_A0B = 0       # start >> 7  (128-block index of the aligned cover base)
S_REM = 1       # start & 127
S_CNT = 2       # number of rows in the leaf range
S_COL = 3       # group row of the split feature in the binned matrix
S_BSTART = 4    # bundled bin offset
S_ISB = 5       # feature is bundled (0/1)
S_NB = 6        # feature num_bin
S_DBIN = 7      # feature default bin
S_MTYPE = 8     # missing type (0 none / 1 zero / 2 nan)
S_THR = 9       # split threshold (bin)
S_DL = 10       # default_left (0/1)
N_SCALARS = 11

def sc_rows_for(g32: int) -> int:
    """Packed-scratch sublanes for a (g32, N) bin matrix: the packed
    words plus up to 8 live ghi rows, rounded to the 32-bit DMA tile."""
    return ((g32 // 4 + 8 + 7) // 8) * 8


SC_ROWS = sc_rows_for(32)   # the common g32=32 geometry


def _excl_prefix_rights(flag_l, C):
    """Exclusive per-lane prefix count of rights (flag_l == 0), via
    strictly-lower-triangular one-hot matmuls on the MXU (cumsum does
    not lower in Pallas TPU)."""
    nb = C // 128
    r = (1 - flag_l).astype(jnp.float32).reshape(nb, 128)
    lt = (jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) <
          jax.lax.broadcasted_iota(jnp.int32, (128, 128), 1)
          ).astype(jnp.float32)
    within = jax.lax.dot_general(
        r, lt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # (nb, 128) exclusive
    tot = jnp.sum(r, axis=1, keepdims=True)          # (nb, 1)
    ltb = (jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 0) <
           jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
           ).astype(jnp.float32)
    carry = jax.lax.dot_general(
        tot.reshape(1, nb), ltb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # (1, nb) excl blocks
    return (within + carry.reshape(nb, 1)).reshape(1, C).astype(jnp.int32)


def _compact(payload, flag, shift0, C, logc):
    """Stable compaction of flagged lanes to the front: binary shift
    network, moving each flagged lane left by its deficit (the number of
    unflagged lanes before it).  Monotone deficits make every step
    collision-free; unflagged lanes are treated as holes.

    The live flag rides bit 16 of the shift vector so each step rolls and
    selects ONE metadata row instead of two (deficits < C <= 2^15)."""
    cur = payload
    live = jnp.int32(1 << 16)
    meta = jnp.where(flag != 0, shift0 | live, 0)
    for b in range(logc):
        bit = 1 << b
        move = jnp.where((meta & live) != 0, meta & bit, 0)
        m_in = pltpu_roll(move, C - bit) != 0
        cur = jnp.where(m_in, pltpu_roll(cur, C - bit), cur)
        meta = jnp.where(m_in, pltpu_roll(meta, C - bit),
                         jnp.where(move != 0, meta & (live - 1), meta))
    return cur


def _compact_radix4(payload, flag, shift0, C, logc):
    """Same contract as ``_compact`` but consuming the deficit TWO bits
    per step (radix-4): ceil(logc/2) network steps instead of logc.

    Each merged step moves every live lane by digit * 4^k where digit is
    the lane's k-th base-4 deficit digit.  Destinations after a merged
    step equal the binary network's positions after its two constituent
    steps, which are collision-free, so the merged move is injective on
    live lanes and the roll-select mechanism stays sound.  The metadata
    row rides the SAME rolls as the payload (one (P+1, C) roll per
    distance instead of separate payload+meta rolls), so a step costs 3
    rolls + 3 selects where two binary steps cost 4 rolls + 4 selects
    plus twice the mask arithmetic — the partition kernel is
    VPU-issue-bound on per-step fixed work, not element throughput
    (PERF.md round 5), which is what this trades for.
    """
    live = jnp.int32(1 << 16)
    meta = jnp.where(flag != 0, shift0 | live, 0)
    aug = jnp.concatenate([payload, meta], axis=0)
    P = payload.shape[0]

    def dig_of(mrow, k, mask_d):
        d = jax.lax.shift_right_logical(
            mrow & (live - 1), jnp.broadcast_to(k, mrow.shape)) & mask_d
        return jnp.where((mrow & live) != 0, d, 0)

    for k in range(0, logc, 2):
        s = 1 << k
        nd = 2 if k + 1 < logc else 1      # bits consumed this step
        mask_d = (1 << nd) - 1
        d_self = dig_of(aug[P:P + 1], k, mask_d)
        r1 = pltpu_roll(aug, C - s)
        m1 = dig_of(r1[P:P + 1], k, mask_d) == 1
        # an element that moves away and is not overwritten leaves a
        # hole: clear its live bit (mirrors the binary network)
        base = jnp.concatenate(
            [aug[0:P],
             jnp.where(d_self != 0, aug[P:P + 1] & (live - 1),
                       aug[P:P + 1])], axis=0)
        if nd == 2:
            r2 = pltpu_roll(aug, C - 2 * s)
            r3 = pltpu_roll(aug, C - 3 * s)
            m2 = dig_of(r2[P:P + 1], k, mask_d) == 2
            m3 = dig_of(r3[P:P + 1], k, mask_d) == 3
            aug = jnp.where(m1, r1,
                            jnp.where(m2, r2, jnp.where(m3, r3, base)))
        else:
            aug = jnp.where(m1, r1, base)
    return aug[0:P]


def payload_codecs(G32: int, ghi_live: int, pack_rowid: bool):
    """Packed-payload codec closures shared by the partition kernel and
    the split mega-kernel (ops/split_megakernel_pallas.py).

    Returns (P, W, pack_bins, unpack_bins, make_payload, split_payload):
    W = G32 // 4 packed bin words; P = compaction payload sublanes.  All
    row picks are STATIC sublane slices — masked row selects/reductions
    take a per-tile slow path in Mosaic (round-5 measurement: an
    iota-compare formulation of the rowid packing ran 15x slower).
    """
    W = G32 // 4
    P = W + ghi_live - (1 if pack_rowid else 0)

    def pack_bins(bins_i32):
        """(G32, C) i32 byte values -> (W, C) packed words."""
        return (bins_i32[0:W] | (bins_i32[W:2 * W] << 8) |
                (bins_i32[2 * W:3 * W] << 16) | (bins_i32[3 * W:4 * W] << 24))

    def unpack_bins(packed):
        """(W, C) packed words -> (G32, C) i32 byte values."""
        return jnp.concatenate(
            [packed & 255, (packed >> 8) & 255,
             (packed >> 16) & 255, (packed >> 24) & 255], axis=0)

    def make_payload(packed, ghi_i):
        """(P, C) compaction payload from packed words + live ghi rows;
        with pack_rowid the rowid bytes overwrite the zero byte-3 slots
        of words W-4..W-1 and ghi row 2 is dropped."""
        if not pack_rowid:
            return jnp.concatenate([packed, ghi_i], axis=0)
        rowid = ghi_i[2:3]                               # (1, C) i32
        top = [packed[W - 4 + j:W - 3 + j] |
               ((jax.lax.shift_right_logical(
                   rowid, jnp.broadcast_to(8 * j, rowid.shape)) & 255)
                << 24)
               for j in range(4)]
        extra = [ghi_i[3:ghi_live]] if ghi_live > 3 else []
        return jnp.concatenate(
            [packed[0:W - 4]] + top + [ghi_i[0:2]] + extra, axis=0)

    def split_payload(pay):
        """(P, C) payload -> ((W, C) clean packed words, (ghi_live, C)
        ghi rows in storage order), reconstructing the rowid row."""
        if not pack_rowid:
            return pay[0:W], pay[W:P]
        rowid = None
        for j in range(4):
            byte_j = (jax.lax.shift_right_logical(
                pay[W - 4 + j:W - 3 + j],
                jnp.broadcast_to(24, (1, pay.shape[1]))) & 255) << (8 * j)
            rowid = byte_j if rowid is None else rowid | byte_j
        packed = jnp.concatenate(
            [pay[0:W - 4], pay[W - 4:W] & 0x00FFFFFF], axis=0)
        tail = [pay[W + 2:P]] if P > W + 2 else []
        ghi = jnp.concatenate([pay[W:W + 2], rowid] + tail, axis=0)
        return packed, ghi

    return P, W, pack_bins, unpack_bins, make_payload, split_payload


def pltpu_roll(x, shift):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.roll(x, shift, 1)


def _cdiv(a, c):
    return jax.lax.div(a + (c - 1), c)


def _decide_left(colv, bstart, isb, nb, dbin, mtype, thr, dl):
    """Numerical split decision on raw group-column values, all-i32
    (bool vectors with Python-literal branches trip an i8->i1
    truncation Mosaic can't lower).  The ONE copy of this arithmetic
    shared by the partition kernel, the split mega-kernel and its XLA
    oracle (ops/split_megakernel_pallas.py) — the mega path's
    bit-exactness contract rides on all of them agreeing; the XLA
    fallback formulation lives in ops/partition.py split_decision /
    models/learner.py _goes_left."""
    fb_raw = colv - bstart
    in_rb = (fb_raw >= 1) & (fb_raw <= nb - 1)
    fb = jnp.where(isb == 1, jnp.where(in_rb, fb_raw, dbin), colv)
    miss_i = jnp.where(
        mtype == 1, (fb == dbin).astype(jnp.int32),
        jnp.where(mtype == 2, (fb == nb - 1).astype(jnp.int32), 0))
    nat_i = (fb <= thr).astype(jnp.int32)
    return jnp.where(miss_i != 0, dl, nat_i)


def partition_leaf_pallas(part_bins, part_ghi, sc_packed, scalars, *,
                          row_chunk: int, ghi_live: int = 3,
                          pack_rowid: bool = False,
                          compact_radix: bool = False,
                          interpret: bool = False):
    """Two-way stable partition of the leaf range described by
    ``scalars`` (see the S_* layout above), in place.

    Args:
      part_bins: (G32, N_pad) u8 binned matrix, G32 a multiple of 32.
      part_ghi:  (8, N_pad)  f32 packed (grad, hess, rowid-bits, ...).
        Only rows 0..ghi_live-1 are preserved through the partition; the
        trailing pad rows come back zeroed/garbage.  The physical-order
        fused training step rides score and objective payload rows here
        (models/boosting.py _setup_fused_step).
      sc_packed: (SC_ROWS, N_pad) i32 scratch staging the packed rights
      scalars: (N_SCALARS,) i32.
      pack_rowid: ride the rowid-bits ghi row (row 2) inside the 4 spare
        byte slots of the packed bin words (byte 3 of words W-4..W-1 —
        the zero pad rows G..G32) instead of as its own payload sublane.
        The roll network's cost is proportional to payload sublanes
        (PERF.md), so this drops P by one for free when G <= G32-4.
        Kernel-internal only: the HBM layout of part_ghi is unchanged
        and the pad bin rows come back zeroed.
      compact_radix: use the radix-4 compaction network
        (``_compact_radix4``: half the network steps) instead of the
        binary one.  Bit-identical output; an issue-budget lever only.
    Returns (part_bins', part_ghi', sc_packed', nl) with the first three
    aliased in place; nl is an (8, 128) i32 tile whose [0, 0] element is
    the left count.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    G32, Np = part_bins.shape
    GH = part_ghi.shape[0]
    assert GH == 8 and G32 % 32 == 0, (G32, GH)
    SCR = sc_packed.shape[0]
    assert (sc_packed.shape[1] == Np and SCR % 8 == 0
            and sc_packed.dtype == jnp.int32)
    C = row_chunk
    assert C >= 256 and (C & (C - 1)) == 0 and Np % 128 == 0
    logc = C.bit_length() - 1
    assert 3 <= ghi_live <= GH
    if pack_rowid:
        assert G32 // 4 >= 4, "pack_rowid needs >= 4 packed words"
    # payload sublanes: bins words + live ghi rows (minus the rowid row
    # when it rides inside the spare bin bytes)
    P, W, pack_bins, unpack_bins, make_payload, split_payload = \
        payload_codecs(G32, ghi_live, pack_rowid)
    assert P <= SCR
    compact = _compact_radix4 if compact_radix else _compact

    def kernel(s_ref, pb_in, pg_in, sp_in, pb, pg, sp, nl_ref,
               rb, rg, rs, stgl, stgr, wb, wg, wp, exb, exg, sems):
        a0b = s_ref[S_A0B]
        rem = s_ref[S_REM]
        cnt = s_ref[S_CNT]
        col = s_ref[S_COL]
        total = rem + cnt
        n_chunks = jnp.where(cnt > 0, _cdiv(total, C), 0)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
        # split column lives at byte (col // W) of packed word (col % W)
        col_k = jax.lax.div(col, W)
        col_w = col - col_k * W
        col_sh = col_k * 8
        word_oh = (jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0) == col_w
                   ).astype(jnp.int32)

        def start_read(ci, slot):
            pltpu.make_async_copy(
                pb_in.at[:, pl.ds(a0b * 128 + ci * C, C)],
                rb.at[slot], sems.at[slot, 0]).start()
            pltpu.make_async_copy(
                pg_in.at[:, pl.ds(a0b * 128 + ci * C, C)],
                rg.at[slot], sems.at[slot, 1]).start()

        def wait_read(slot):
            pltpu.make_async_copy(
                pb_in.at[:, pl.ds(0, C)], rb.at[slot],
                sems.at[slot, 0]).wait()
            pltpu.make_async_copy(
                pg_in.at[:, pl.ds(0, C)], rg.at[slot],
                sems.at[slot, 1]).wait()

        @pl.when(n_chunks > 0)
        def _():
            start_read(0, 0)

        def body(ci, carry):
            fill_l, fill_r, nfl, nfr, nl_cnt = carry
            slot = jax.lax.rem(ci, 2)

            @pl.when(ci + 1 < n_chunks)
            def _():
                start_read(ci + 1, 1 - slot)
            wait_read(slot)

            bins_i = rb[slot].astype(jnp.int32)               # (G32, C)
            packed = pack_bins(bins_i)                        # (W, C)
            ghi_i = jax.lax.bitcast_convert_type(
                rg[slot], jnp.int32)[0:ghi_live]
            payload = make_payload(packed, ghi_i)             # (P, C)

            # --- decision (numerical splits; see ops/partition.py
            # split_decision and models/learner.py _goes_left) ---
            word = jnp.sum(packed * word_oh, axis=0,
                           keepdims=True)                     # (1, C)
            colv = jax.lax.shift_right_logical(
                word, jnp.broadcast_to(col_sh, word.shape)) & 255
            gl_i = _decide_left(colv, s_ref[S_BSTART], s_ref[S_ISB],
                                s_ref[S_NB], s_ref[S_DBIN],
                                s_ref[S_MTYPE], s_ref[S_THR], s_ref[S_DL])

            pos = ci * C + lane                 # cover-relative position
            before_i = (pos < rem).astype(jnp.int32)
            inside_i = ((pos >= rem) & (pos < total)).astype(jnp.int32)
            left = jnp.where((before_i != 0) |
                             ((inside_i != 0) & (gl_i != 0)), 1, 0)

            pnr = _excl_prefix_rights(left, C)       # rights before lane
            nlc = jnp.sum(left)
            nl_cnt = nl_cnt + nlc
            nrc = C - nlc

            lcomp = compact(payload, left, pnr, C, logc)
            rcomp = compact(payload, 1 - left, lane - pnr, C, logc)

            def stage(stg, comp, fill, n_add):
                # place comp[0:n_add) at staging positions [fill, +n_add)
                rolled = pltpu.roll(comp, fill, 1)
                m1 = (lane >= fill) & (lane < fill + n_add)
                stg[:, 0:C] = jnp.where(m1, rolled, stg[:, 0:C])
                m2 = (lane + C) < (fill + n_add)
                stg[:, C:2 * C] = jnp.where(m2, rolled, stg[:, C:2 * C])
                new_fill = fill + n_add
                flushed = (new_fill >= C).astype(jnp.int32)
                return new_fill - flushed * C, flushed

            fill_l, fl_l = stage(stgl, lcomp, fill_l, nlc)
            fill_r, fl_r = stage(stgr, rcomp, fill_r, nrc)

            # lefts: unpack and flush in place to the row buffers.
            # Flush DMAs are NOT waited inline: the wait happens just
            # before the NEXT overwrite of the staging window (or at the
            # pass-1 drain), overlapping the write with the next chunk's
            # compaction.  Write windows only ever move forward, so the
            # deferred write still lands strictly behind the read
            # frontier.
            @pl.when(fl_l > 0)
            def _():
                @pl.when(nfl > 0)
                def _():
                    pltpu.make_async_copy(
                        wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
                    pltpu.make_async_copy(
                        wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()
                pk_l, gl_l = split_payload(stgl[:, 0:C])
                wb[:] = unpack_bins(pk_l).astype(jnp.uint8)
                wg[:] = jax.lax.bitcast_convert_type(
                    jnp.concatenate(
                        [gl_l,
                         jnp.zeros((GH - ghi_live, C), jnp.int32)], axis=0),
                    jnp.float32)
                pltpu.make_async_copy(
                    wb, pb.at[:, pl.ds(a0b * 128 + nfl * C, C)],
                    sems.at[0, 2]).start()
                pltpu.make_async_copy(
                    wg, pg.at[:, pl.ds(a0b * 128 + nfl * C, C)],
                    sems.at[1, 2]).start()
                stgl[:, 0:C] = stgl[:, C:2 * C]

            # rights: flush STILL PACKED to the i32 scratch
            @pl.when(fl_r > 0)
            def _():
                @pl.when(nfr > 0)
                def _():
                    pltpu.make_async_copy(
                        wp, sp.at[:, pl.ds(0, C)], sems.at[0, 3]).wait()
                wp[0:P] = stgr[:, 0:C]
                pltpu.make_async_copy(
                    wp, sp.at[:, pl.ds(a0b * 128 + nfr * C, C)],
                    sems.at[0, 3]).start()
                stgr[:, 0:C] = stgr[:, C:2 * C]

            return fill_l, fill_r, nfl + fl_l, nfr + fl_r, nl_cnt

        fill_l, fill_r, nfl, nfr, nl_cnt = jax.lax.fori_loop(
            0, n_chunks, body,
            (jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0),
             jnp.int32(0)))

        # Drain the deferred in-flight flush DMAs before the staging
        # buffers are overwritten and before pass 2 touches their
        # destination regions.
        @pl.when(nfl > 0)
        def _():
            pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
            pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()

        @pl.when(nfr > 0)
        def _():
            pltpu.make_async_copy(
                wp, sp.at[:, pl.ds(0, C)], sems.at[0, 3]).wait()

        # Final partial flushes.  Full-window writes: the garbage tail
        # beyond ``fill`` is always rewritten by pass 2 (lefts) or never
        # read (scratch).
        @pl.when(fill_l > 0)
        def _():
            pk_f, gl_f = split_payload(stgl[:, 0:C])
            wb[:] = unpack_bins(pk_f).astype(jnp.uint8)
            wg[:] = jax.lax.bitcast_convert_type(
                jnp.concatenate(
                    [gl_f,
                     jnp.zeros((GH - ghi_live, C), jnp.int32)], axis=0),
                jnp.float32)
            cb = pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(a0b * 128 + nfl * C, C)], sems.at[0, 2])
            cg = pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(a0b * 128 + nfl * C, C)], sems.at[1, 2])
            cb.start(); cg.start(); cb.wait(); cg.wait()

        @pl.when(fill_r > 0)
        def _():
            wp[0:P] = stgr[:, 0:C]
            cp = pltpu.make_async_copy(
                wp, sp.at[:, pl.ds(a0b * 128 + nfr * C, C)], sems.at[0, 3])
            cp.start(); cp.wait()

        # drop the foreign prefix; with cnt == 0 the chunk loop never ran
        # (trash-slot iterations call the partition with an arbitrary,
        # usually unaligned start), so the count must clamp to 0
        nl_true = jnp.where(cnt > 0, nl_cnt - rem, 0)
        nl_ref[:] = jnp.broadcast_to(nl_true, (8, 128)).astype(jnp.int32)

        # ---- pass 2: slide staged rights into [start+nl, aligned_end) ----
        s_r = n_chunks * C - nl_cnt                  # staged rights total
        dst_off = rem + nl_true                      # dst0 - a0
        dwb = a0b + jax.lax.shift_right_logical(dst_off, 7)  # block of dw0
        # r0 = dst0 - floor128(dst0), in [0, 128)
        r0 = dst_off - jax.lax.shift_right_logical(dst_off, 7) * 128
        n_d = jnp.where(s_r > 0, _cdiv(r0 + s_r, C), 0)
        aligned_total = n_chunks * C                 # cover size

        def body2(j, _):
            slot = jax.lax.rem(j, 2)
            # read source window j of the staged rights (front-packed
            # from the cover base in scratch); the guard keeps the last
            # (prev-only) destination window from reading past the
            # staged region
            read_src = j * C < s_r

            @pl.when(read_src)
            def _():
                # read through the OUTPUT refs: on TPU they alias the
                # inputs, and the snapshot semantics of interpret mode
                # would otherwise show pass 2 stale pre-pass-1 contents
                pltpu.make_async_copy(
                    sp.at[:, pl.ds(a0b * 128 + j * C, C)],
                    rs.at[slot], sems.at[slot, 0]).start()
            # destination window bounds (cover-relative)
            dlo = dst_off - r0 + j * C               # window start
            lo = jnp.where(j == 0, r0, 0)
            hi = jnp.minimum(C, aligned_total - dlo)
            need_rmw = (lo > 0) | (hi < C)

            @pl.when(need_rmw)
            def _():
                cb = pltpu.make_async_copy(
                    pb.at[:, pl.ds(dwb * 128 + j * C, C)], exb,
                    sems.at[0, 3])
                cg = pltpu.make_async_copy(
                    pg.at[:, pl.ds(dwb * 128 + j * C, C)], exg,
                    sems.at[1, 3])
                cb.start(); cg.start(); cb.wait(); cg.wait()

            @pl.when(read_src)
            def _():
                pltpu.make_async_copy(
                    sp.at[:, pl.ds(0, C)], rs.at[slot],
                    sems.at[slot, 0]).wait()

            cur_p = rs[slot][0:P]                    # packed payload
            prv_p = rs[1 - slot][0:P]
            take_prev = lane < r0
            out_p = jnp.where(take_prev, pltpu.roll(prv_p, r0, 1),
                              pltpu.roll(cur_p, r0, 1))
            pk_2, out_gl = split_payload(out_p)      # clean words + ghi
            out_b = unpack_bins(pk_2)                # (G32, C)
            valid = (lane >= lo) & (lane < hi)
            # wait the PREVIOUS window's deferred write before reusing
            # the staging buffers (destination windows are disjoint, so
            # the in-flight write never races this window's RMW read)
            @pl.when(j > 0)
            def _():
                pltpu.make_async_copy(
                    wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
                pltpu.make_async_copy(
                    wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()
            exg_i = jax.lax.bitcast_convert_type(exg[:], jnp.int32)
            wb[:] = jnp.where(valid, out_b,
                              exb[:].astype(jnp.int32)).astype(jnp.uint8)
            wg[:] = jax.lax.bitcast_convert_type(
                jnp.concatenate(
                    [jnp.where(valid, out_gl, exg_i[0:ghi_live]),
                     exg_i[ghi_live:GH]],
                    axis=0),
                jnp.float32)
            pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(dwb * 128 + j * C, C)],
                sems.at[0, 2]).start()
            pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(dwb * 128 + j * C, C)],
                sems.at[1, 2]).start()
            return 0

        jax.lax.fori_loop(0, n_d, body2, 0)

        @pl.when(n_d > 0)
        def _():
            pltpu.make_async_copy(
                wb, pb.at[:, pl.ds(0, C)], sems.at[0, 2]).wait()
            pltpu.make_async_copy(
                wg, pg.at[:, pl.ds(0, C)], sems.at[1, 2]).wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 3,
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 3 +
                  [pl.BlockSpec(memory_space=pltpu.VMEM)],
        scratch_shapes=[
            pltpu.VMEM((2, G32, C), jnp.uint8),      # rb
            pltpu.VMEM((2, GH, C), jnp.float32),     # rg
            pltpu.VMEM((2, SCR, C), jnp.int32),      # rs
            pltpu.VMEM((P, 2 * C), jnp.int32),       # stgl
            pltpu.VMEM((P, 2 * C), jnp.int32),       # stgr
            pltpu.VMEM((G32, C), jnp.uint8),         # wb
            pltpu.VMEM((GH, C), jnp.float32),        # wg
            pltpu.VMEM((SCR, C), jnp.int32),         # wp
            pltpu.VMEM((G32, C), jnp.uint8),         # exb
            pltpu.VMEM((GH, C), jnp.float32),        # exg
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(part_bins.shape, part_bins.dtype),
            jax.ShapeDtypeStruct(part_ghi.shape, part_ghi.dtype),
            jax.ShapeDtypeStruct(sc_packed.shape, sc_packed.dtype),
            jax.ShapeDtypeStruct((8, 128), jnp.int32),
        ],
        grid_spec=grid_spec,
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(scalars, part_bins, part_ghi, sc_packed)
    return out


def make_scalars(start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl):
    """Pack the kernel's scalar operand (all traced i32)."""
    start = jnp.asarray(start, jnp.int32)
    a0b = jax.lax.shift_right_logical(start, 7)
    rem = start - a0b * 128
    vals = [a0b, rem, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl]
    return jnp.stack([jnp.asarray(v).astype(jnp.int32) for v in vals])
