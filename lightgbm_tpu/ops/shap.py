"""On-device vectorized TreeSHAP over padded per-tree path matrices.

The exact TreeSHAP recursion (models/shap.py, reference tree.h TreeSHAP)
spends its time in sequential extend/unwind loops whose state is a
polynomial in the "subset size" weight variable.  Per leaf, that
polynomial factorizes over the unique path elements — element j
contributes the linear factor

    hot_j : t + zf_j * (1 - t)        (row agrees with the path)
    cold_j: zf_j * (1 - t)            (row routed away)

and the unwound path sum for element i is exactly

    w_i = integral_0^1  [ prod_j factor_j(t) ] / factor_i(t)  dt,

a polynomial of degree <= D-1, integrated EXACTLY by Gauss-Legendre
quadrature with ceil(D/2) points (verified to ~1e-16 against the
recursion).  That re-expresses the whole computation as dense
per-(element, row) array ops with no sequential unwinds: one decision
evaluation per (node, row), one product over path elements, one
division per element — the same restructuring GPUTreeShap applies to
put TreeSHAP on accelerators (Mitchell et al., arXiv:2010.13972), with
the quadrature trick replacing its warp-level psums.

Rows ride the LANE (last) axis like every kernel in ops/ (see
ops/predict.py).  Decisions are evaluated in bin space from the same
node arrays the device predictor uses, so device contributions are
exact for in-session trees; the host recursion stays the oracle (and
the fallback for loaded/linear models).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .partition import split_decision


def leggauss_01(max_path_len: int):
    """Gauss-Legendre nodes/weights on [0, 1] exact for the kernel's
    degree <= max_path_len - 1 integrands (q points integrate degree
    2q - 1 exactly)."""
    q = (max(max_path_len, 1) + 1) // 2
    x, w = np.polynomial.legendre.leggauss(q)
    return 0.5 * (x + 1.0), 0.5 * w


def node_decisions(binned_t: jnp.ndarray, node: Dict[str, jnp.ndarray]
                   ) -> jnp.ndarray:
    """Goes-left decision at EVERY node for every row: (V, n) bool.

    Same per-node formula as the predict traversal's loop body
    (ops/predict.py predict_leaf_binned), evaluated for all nodes at
    once instead of along each row's path."""
    gb = jnp.take(binned_t, node["col"], axis=0)          # (V, n)
    bin_start = node["bin_start"][:, None]
    nb = node["num_bin"][:, None]
    default_bin = node["default_bin"][:, None]
    fb_raw = gb - bin_start
    in_range = (fb_raw >= 1) & (fb_raw <= nb - 1)
    fb = jnp.where(node["is_bundled"][:, None] == 1,
                   jnp.where(in_range, fb_raw, default_bin), gb)
    goes_left = split_decision(
        fb, node["threshold"][:, None],
        node["default_left"][:, None] == 1,
        node["missing_type"][:, None], default_bin, nb - 1)
    if "is_cat" in node:
        member = jnp.take_along_axis(
            node["cat_set"],
            jnp.minimum(fb, node["cat_set"].shape[1] - 1), axis=1)
        member = member & (fb <= nb - 1)
        goes_left = jnp.where(node["is_cat"][:, None] == 1, member,
                              goes_left)
    return goes_left


def tree_shap_stacked(binned: jnp.ndarray, nodes: Dict[str, jnp.ndarray],
                      paths: Dict[str, jnp.ndarray],
                      tree_mask: jnp.ndarray, t_nodes: jnp.ndarray,
                      t_weights: jnp.ndarray,
                      num_columns: int) -> jnp.ndarray:
    """SHAP contributions of a stacked forest: (n, num_columns).

    Args:
      binned: (n, G) integer group-bin matrix.
      nodes: per-node arrays stacked over trees, each (T, V) (+ optional
        ``is_cat`` (T, V) and ``cat_set`` (T, V, W)).
      paths: padded path matrices stacked over trees (models/shap.py
        tree_path_arrays): ``zf`` (T, L, D), ``feat`` (T, L, D),
        ``node`` (T, L, D, M), ``dir`` (T, L, D, M),
        ``leaf_value`` (T, L).
      tree_mask: (T,) 0/1 — start/num_iteration slicing without a
        retrace (masked trees contribute nothing).
      t_nodes / t_weights: quadrature rule from :func:`leggauss_01`.
      num_columns: num_features + 1 (the bias column stays zero here;
        the engine adds the row-independent expected values on host).
    """
    n = binned.shape[0]
    binned_t = binned.T.astype(jnp.int32)                 # (G, n)
    t_nodes = jnp.asarray(t_nodes)
    # the quadrature rule's dtype selects the kernel precision: f64 under
    # an enable_x64 context (exact-parity serving), f32 on TPU
    dtype = t_nodes.dtype
    t_weights = jnp.asarray(t_weights, dtype)
    one = jnp.asarray(1.0, dtype)

    nq = int(t_nodes.shape[0])

    def body(phi_acc, per_tree):
        node, path, mask = per_tree
        gl = node_decisions(binned_t, node)               # (V, n)
        conds = path["node"]                              # (L, D, M)
        L, D, M = conds.shape
        # hot = AND over the element's merged-node conditions, one
        # (L, D, n) slab per slot (an (L, D, M, n) materialization
        # streams to DRAM for deep duplicate-heavy trees)
        hot = None
        for m in range(M):
            dirm = path["dir"][:, :, m][:, :, None]       # (L, D, 1)
            glm = jnp.take(gl, conds[:, :, m].reshape(-1),
                           axis=0).reshape(L, D, n)
            agree = (dirm == 2) | (glm == (dirm == 1))
            hot = agree if hot is None else hot & agree
        hot = hot.astype(dtype)                           # (L, D, n)
        zf = path["zf"].astype(dtype)                     # (L, D)
        # per-element linear factor in FMA form: hot elements contribute
        # t + zf*(1-t), cold ones zf*(1-t) — i.e. zf*(1-t) + hot*t.
        # The (q, L, D, n) factor tensor is NEVER materialized: the D and
        # q loops unroll at trace time and each factor slice is
        # recomputed on the fly from the (L, D, n) hot mask and tiny
        # row-independent (L, D) tables, keeping the working set at
        # (L, n) — cache-resident instead of DRAM-streaming (measured
        # ~4x on the 2-core CPU host vs the materialized form).
        zf1mt = [zf * (one - t_nodes[qi]) for qi in range(nq)]  # (L, D)
        # pass 1: full path product Q_q = prod_d fac_{q,d}
        Q = []
        for qi in range(nq):
            acc = None
            for d in range(D):
                fac = zf1mt[qi][:, d, None] + hot[:, d, :] * t_nodes[qi]
                acc = fac if acc is None else acc * fac
            Q.append(acc * t_weights[qi])                 # (L, n)
        # pass 2: unwound sums w_d = sum_q om_q * Q_q / fac_{q,d}
        # (every factor is >= min(zf)*(1-t_max) > 0 at the interior
        # quadrature nodes, so the division is safe)
        wcols = []
        for d in range(D):
            acc = None
            for qi in range(nq):
                fac = zf1mt[qi][:, d, None] + hot[:, d, :] * t_nodes[qi]
                term = Q[qi] / fac
                acc = term if acc is None else acc + term
            wcols.append(acc)                             # (L, n)
        w = jnp.stack(wcols, axis=1)                      # (L, D, n)
        contrib = (w * (hot - zf[:, :, None])
                   * path["leaf_value"][:, None, None].astype(dtype))
        # per-feature scatter as one (F, L*D) x (L*D, n) matmul — the
        # contraction layout the CPU/TPU dot engines take directly
        onehot_t = (jnp.arange(num_columns)[:, None]
                    == path["feat"].reshape(1, L * D)).astype(dtype)
        phi = jnp.matmul(onehot_t, contrib.reshape(L * D, n))
        return phi_acc + mask.astype(dtype) * phi, None

    phi0 = jnp.zeros((num_columns, n), dtype)
    phi, _ = jax.lax.scan(body, phi0, (nodes, paths, tree_mask))
    return phi.T
