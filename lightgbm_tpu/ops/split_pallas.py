"""Pallas TPU kernel for the all-numerical best-split search.

One program per split evaluates BOTH children of the freshly split leaf:
the while-body's split search is op-dispatch-bound on this stack
(~80 us/split as ~25 XLA ops, PERF.md), while the actual compute is
trivial — one (12F, BF) prefix-sum matmul on the MXU and a few VPU
passes over (2F, BF) grids.  Collapsing it into a single all-VMEM
pallas_call (no DMAs, no scalar prefetch — the kernel class that
compiles through the remote Mosaic toolchain) removes the dispatch
overhead.

Semantics match ops/split.py:find_best_split_fast (itself equivalent to
the reference FindBestThresholdSequentially dispatch,
feature_histogram.hpp:272-455):
  * forward scan (missing right) and reverse scan (missing left) with
    MissingType::Zero default-bin skipping and the NaN-bin exclusion;
  * the reference's scan-order tie-breaking is encoded as a
    per-candidate PREFERENCE KEY (feature-major; within a feature the
    reverse scan's thresholds descending, then the forward scan's
    ascending): the winner is the minimum key among maximum-gain
    candidates, so no lane reversal is needed in-kernel;
  * counts ride f32 (exact below 2^24 rows);
  * the depth guard (models/learner.py _depth_guard) is folded into the
    candidate validity mask.

The output tile rows are the packed leafmat column segment
[LM_BGAIN..LM_BISCAT] (models/learner.py) for the left (row 0) and
right (row 1) child, with int fields bitcast into the f32 container —
the caller splices them into the leaf matrix with one dynamic update
per child.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

K_EPSILON = 1e-15

# fmeta columns (per stacked child-feature row)
FM_NUM_BIN = 0
FM_MISSING = 1
FM_DEFAULT = 2

# info columns (per stacked child-feature row)
IN_SUM_G = 0
IN_SUM_H = 1
IN_NUM_DATA = 2
IN_DEPTH = 3
IN_MASK = 4

OUT_FIELDS = 13     # lanes of each output row = LM_BGAIN..LM_BISCAT


@functools.partial(jax.jit, static_argnames=(
    "l1", "l2", "max_delta_step", "min_gain_to_split", "min_data_in_leaf",
    "min_sum_hessian", "max_depth", "interpret"))
def best_split_pair_pallas(hist_g, hist_h, fmeta, info,
                           *, l1: float, l2: float, max_delta_step: float,
                           min_gain_to_split: float, min_data_in_leaf: int,
                           min_sum_hessian: float, max_depth: int,
                           interpret: bool = False):
    """Best numerical split for two sibling leaves.

    Args:
      hist_g / hist_h: (2F, BF) f32 — gradient / hessian histograms;
        the left child's F feature rows stacked above the right child's.
      fmeta: (2F, 8) i32 — FM_* columns (static per-feature metadata,
        duplicated per child block).
      info: (2F, 8) f32 — IN_* columns (per-split leaf scalars broadcast
        over each child block; IN_MASK is the per-child feature mask).
    Returns an (8, 128) f32 tile; rows 0/1 hold the children's packed
    leafmat segments (see module docstring).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F2, BF = hist_g.shape
    F = F2 // 2
    NEG = float("-inf")

    def thr_l1(g):
        # sign(g)*max(0,|g|-l1) without jnp.sign (untested lowering);
        # the where-form is identical (both give 0 at g == 0)
        mag = jnp.maximum(0.0, jnp.abs(g) - l1)
        return jnp.where(g < 0, -mag, mag)

    def leaf_out(g, h):
        ret = -thr_l1(g) / (h + l2)
        if max_delta_step > 0:
            ret = jnp.clip(ret, -max_delta_step, max_delta_step)
        return ret

    def leaf_gain(g, h):
        s = thr_l1(g)
        if max_delta_step > 0:
            out = leaf_out(g, h)
            return -(2.0 * s * out + (h + l2) * out * out)
        return s * s / (h + l2)

    def kernel(hg_ref, hh_ref, fm_ref, li_ref, out):
        hg = hg_ref[:]
        hh = hh_ref[:]
        nb2 = fm_ref[:, FM_NUM_BIN:FM_NUM_BIN + 1]        # (2F, 1)
        mtype2 = fm_ref[:, FM_MISSING:FM_MISSING + 1]
        dflt2 = fm_ref[:, FM_DEFAULT:FM_DEFAULT + 1]
        sum_g = li_ref[:, IN_SUM_G:IN_SUM_G + 1]          # (2F, 1)
        sum_h_tot = li_ref[:, IN_SUM_H:IN_SUM_H + 1] + 2 * K_EPSILON
        num_data = li_ref[:, IN_NUM_DATA:IN_NUM_DATA + 1]
        depth = li_ref[:, IN_DEPTH:IN_DEPTH + 1]
        fmask2 = (li_ref[:, IN_MASK:IN_MASK + 1] > 0).astype(jnp.int32)
        cnt_factor = num_data / sum_h_tot

        bins = jax.lax.broadcasted_iota(jnp.int32, (F2, BF), 1)
        in_range_i = (bins < nb2).astype(jnp.int32)
        zero_i = (mtype2 == 1).astype(jnp.int32)
        nan_i = (mtype2 == 2).astype(jnp.int32)
        two_scan_i = ((nb2 > 2) & (mtype2 != 0)).astype(jnp.int32)
        cnt_bin = jnp.floor(hh * cnt_factor + 0.5) * in_range_i

        at_dflt_i = (bins == dflt2).astype(jnp.int32)
        mf = (in_range_i * (1 - zero_i * at_dflt_i)).astype(jnp.float32)
        bmax = nb2 - 1 - nan_i * two_scan_i
        mr = (in_range_i * (1 - two_scan_i * zero_i * at_dflt_i) *
              (bins <= bmax).astype(jnp.int32)).astype(jnp.float32)

        stacked = jnp.concatenate([
            hg * mf, hh * mf, cnt_bin * mf,
            hg * mr, hh * mr, cnt_bin * mr], axis=0)       # (12F, BF)
        tri = (jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 0) <=
               jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 1)
               ).astype(jnp.float32)
        cs = jax.lax.dot_general(
            stacked, tri, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (12F, BF)

        lg_f = cs[0:F2]
        lh_f = cs[F2:2 * F2] + K_EPSILON
        lc_f = cs[2 * F2:3 * F2]
        rg_f = sum_g - lg_f
        rh_f = sum_h_tot - lh_f
        rc_f = num_data - lc_f

        cg_r = cs[3 * F2:4 * F2]
        ch_r = cs[4 * F2:5 * F2]
        cc_r = cs[5 * F2:6 * F2]
        # totals from the prefix matmul's LAST column: a separate sum
        # reduce rounds differently and the right-side subtraction
        # amplifies the mismatch vs the XLA fast search
        tot_g = cg_r[:, BF - 1:BF]
        tot_h = ch_r[:, BF - 1:BF]
        tot_c = cc_r[:, BF - 1:BF]
        rg_r = tot_g - cg_r
        rh_r = tot_h - ch_r + K_EPSILON
        rc_r = tot_c - cc_r
        lg_r = sum_g - rg_r
        lh_r = sum_h_tot - rh_r
        lc_r = num_data - rc_r

        gain_f = leaf_gain(lg_f, lh_f) + leaf_gain(rg_f, rh_f)
        gain_r = leaf_gain(lg_r, lh_r) + leaf_gain(rg_r, rh_r)

        gain_shift = leaf_gain(sum_g, sum_h_tot)           # (2F, 1)
        mgs = gain_shift + min_gain_to_split
        mdl = jnp.float32(min_data_in_leaf)

        def cvalid(lc, rc, lh, rh):
            return ((lc >= mdl).astype(jnp.int32) *
                    (rc >= mdl).astype(jnp.int32) *
                    (lh >= min_sum_hessian).astype(jnp.int32) *
                    (rh >= min_sum_hessian).astype(jnp.int32))

        valid_f = (two_scan_i * in_range_i *
                   (bins <= nb2 - 2).astype(jnp.int32) *
                   (1 - zero_i * at_dflt_i) *
                   cvalid(lc_f, rc_f, lh_f, rh_f) *
                   (gain_f > mgs).astype(jnp.int32) * fmask2)
        valid_r = (in_range_i * (bins <= bmax - 1).astype(jnp.int32) *
                   (1 - two_scan_i * zero_i *
                    (bins == dflt2 - 1).astype(jnp.int32)) *
                   cvalid(lc_r, rc_r, lh_r, rh_r) *
                   (gain_r > mgs).astype(jnp.int32) * fmask2)
        if max_depth > 0:
            depth_ok = (depth < max_depth).astype(jnp.int32)
            valid_f = valid_f * depth_ok
            valid_r = valid_r * depth_ok

        gf = jnp.where(valid_f != 0, gain_f, NEG)
        gr = jnp.where(valid_r != 0, gain_r, NEG)

        # preference keys (feature-major; rev desc-t then fwd asc-t)
        feat = jax.lax.broadcasted_iota(jnp.int32, (F2, BF), 0)
        feat = jnp.where(feat >= F, feat - F, feat)
        pref_r = feat * (2 * BF) + (BF - 1 - bins)
        pref_f = feat * (2 * BF) + BF + bins
        # single-scan NaN features flip default_left off for reverse
        # winners (find_best_split_fast dl_r); kept as a (2F, 1) column —
        # materializing it as a broadcast grid crashes Mosaic
        snan_col = ((1 - two_scan_i) * nan_i).astype(jnp.float32)

        acc = jnp.zeros((8, 128), jnp.float32)
        rows8 = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
        lanes8 = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
        for c in range(2):
            s = slice(c * F, (c + 1) * F)
            gmax = jnp.maximum(jnp.max(gf[s]), jnp.max(gr[s]))
            key_r = jnp.where(gr[s] >= gmax, pref_r[s], jnp.int32(1 << 30))
            key_f = jnp.where(gf[s] >= gmax, pref_f[s], jnp.int32(1 << 30))
            win = jnp.minimum(jnp.min(key_r), jnp.min(key_f))
            sel_r = (key_r == win).astype(jnp.float32)
            sel_f = (key_f == win).astype(jnp.float32)

            def pick(a_r, a_f, s=s, sel_r=sel_r, sel_f=sel_f):
                return jnp.sum(a_r[s] * sel_r) + jnp.sum(a_f[s] * sel_f)

            lg = pick(lg_r, lg_f)
            lh = pick(lh_r, lh_f)
            lc = pick(lc_r, lc_f)
            wfeat = win // (2 * BF)
            r = win - wfeat * (2 * BF)
            is_rev_i = (r < BF).astype(jnp.int32)
            thr = jnp.where(is_rev_i != 0, BF - 1 - r, r - BF)
            sel_row = jnp.sum(sel_r, axis=1, keepdims=True)
            snan_pick = jnp.sum(snan_col[s] * sel_row)
            dl = is_rev_i.astype(jnp.float32) * (1.0 - snan_pick)

            sg_c = jnp.max(li_ref[s, IN_SUM_G:IN_SUM_G + 1])
            sh_c = jnp.max(li_ref[s, IN_SUM_H:IN_SUM_H + 1]) \
                + 2 * K_EPSILON
            nd_c = jnp.max(li_ref[s, IN_NUM_DATA:IN_NUM_DATA + 1])
            rg = sg_c - lg
            rh = sh_c - lh
            rc = nd_c - lc
            shift_c = leaf_gain(sg_c, sh_c) + min_gain_to_split
            has_win = (win < (1 << 30)).astype(jnp.float32)
            gain_rel = jnp.where(has_win > 0, gmax - shift_c, NEG)

            def bitf(x):
                # tpu.bitcast needs vector operands; go through (1, 1)
                v = jnp.broadcast_to(x, (1, 1)).astype(jnp.int32)
                return jax.lax.bitcast_convert_type(v, jnp.float32)

            vals = [
                gain_rel,
                bitf(wfeat),
                bitf(thr),
                dl,
                bitf(lc),
                bitf(rc),
                lg, lh - K_EPSILON, rg, rh - K_EPSILON,
                leaf_out(lg, lh), leaf_out(rg, rh),
                jnp.float32(0.0),          # is_cat: numerical only
            ]
            for k, v in enumerate(vals):
                acc = jnp.where((rows8 == c) & (lanes8 == k), v, acc)
        out[:] = acc

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        interpret=interpret,
    )(hist_g, hist_h, fmeta, info)
