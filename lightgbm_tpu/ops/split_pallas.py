"""Pallas TPU kernel for the all-numerical best-split search.

One program per split evaluates BOTH children: the while-body's split
search is op-dispatch-bound on this stack (~80 us/split as ~25 XLA ops,
PERF.md), while the actual compute is trivial — a (336, BF) prefix-sum
matmul and a few VPU passes over (2F, BF) grids.  Collapsing it into a
single all-VMEM pallas_call (no DMAs, no scalar prefetch — the kernel
class that compiles through the remote Mosaic toolchain) removes the
dispatch overhead.

Semantics match ops/split.py:find_best_split_fast (itself equivalent to
the reference FindBestThresholdSequentially dispatch,
feature_histogram.hpp:272-455):
  * forward scan (missing right) and reverse scan (missing left) with
    MissingType::Zero default-bin skipping and the NaN-bin exclusion;
  * tie-breaking encoded as a per-candidate PREFERENCE KEY
    (feature-major; within a feature the reverse scan's thresholds
    descending, then the forward scan's ascending) — the winner is the
    minimum key among maximum-gain candidates, so no lane reversal is
    needed in-kernel;
  * counts ride f32 (exact below 2^24 rows).

The output tile rows are the packed leafmat column segment
[LM_BGAIN..LM_BISCAT] (models/learner.py) for the left (row 0) and
right (row 1) child, with int fields bitcast into the f32 container —
the caller splices them into the leaf matrix with one dynamic update.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

K_EPSILON = 1e-15
NEG = jnp.float32(-jnp.inf)

# output tile rows 0/1 hold, per child, lanes 0..12 =
# [gain, feature(i32), threshold(i32), default_left, lcnt(i32),
#  rcnt(i32), lsg, lsh, rsg, rsh, lout, rout, is_cat] — exactly the
# LM_BGAIN..LM_BISCAT leafmat segment.
OUT_FIELDS = 13


def _i2f(x):
    return jax.lax.bitcast_convert_type(jnp.asarray(x, jnp.int32),
                                        jnp.float32)


@functools.partial(jax.jit, static_argnames=(
    "l1", "l2", "max_delta_step", "min_gain_to_split", "min_data_in_leaf",
    "min_sum_hessian", "max_depth"))
def best_split_pair_pallas(hist_g, hist_h, fmeta, leafinfo, feature_mask,
                           *, l1: float, l2: float, max_delta_step: float,
                           min_gain_to_split: float, min_data_in_leaf: int,
                           min_sum_hessian: float, max_depth: int):
    """Best numerical split for two sibling leaves.

    Args:
      hist_g / hist_h: (2F, BF) f32 — gradient / hessian histograms, the
        left child's F feature rows stacked above the right child's.
      fmeta: (8, F) i32 — rows [num_bin, missing_type, default_bin] (the
        rest pad).
      leafinfo: (8, 128) f32 — per-child scalars at [child, k]:
        k=0 sum_g, 1 sum_h, 2 num_data (f32), 3 depth (f32).
      feature_mask: (1, F) i32 — 1 where the feature may split.
    Returns an (8, 128) f32 tile (see OUT_FIELDS).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F2, BF = hist_g.shape
    F = F2 // 2
    BIG = jnp.float32(3e38)

    def leaf_out(g, h):
        s = jnp.sign(g) * jnp.maximum(0.0, jnp.abs(g) - l1)
        ret = -s / (h + l2)
        if max_delta_step > 0:
            ret = jnp.clip(ret, -max_delta_step, max_delta_step)
        return ret

    def leaf_gain(g, h):
        if max_delta_step > 0:
            out = leaf_out(g, h)
            s = jnp.sign(g) * jnp.maximum(0.0, jnp.abs(g) - l1)
            return -(2.0 * s * out + (h + l2) * out * out)
        s = jnp.sign(g) * jnp.maximum(0.0, jnp.abs(g) - l1)
        return s * s / (h + l2)

    def kernel(hg, hh, fm, li, mask, out):
        nb = jnp.broadcast_to(fm[0:1, :].reshape(F, 1), (F, 1))
        mtype = fm[1:2, :].reshape(F, 1)
        dflt = fm[2:3, :].reshape(F, 1)
        nb2 = jnp.concatenate([nb, nb], axis=0)          # (2F, 1)
        mtype2 = jnp.concatenate([mtype, mtype], axis=0)
        dflt2 = jnp.concatenate([dflt, dflt], axis=0)
        fmask2 = jnp.concatenate(
            [mask[0:1, :].reshape(F, 1), mask[0:1, :].reshape(F, 1)],
            axis=0)                                       # (2F, 1)

        child = (jax.lax.broadcasted_iota(jnp.int32, (F2, 1), 0) >= F
                 ).astype(jnp.int32)                      # 0 left, 1 right
        sum_g = jnp.where(child == 0, li[0, 0], li[1, 0])
        sum_h_tot = jnp.where(child == 0, li[0, 1], li[1, 1]) \
            + 2 * K_EPSILON
        num_data = jnp.where(child == 0, li[0, 2], li[1, 2])
        depth = li[0, 3]
        cnt_factor = num_data / sum_h_tot                 # (2F, 1)

        bins = jax.lax.broadcasted_iota(jnp.int32, (F2, BF), 1)
        in_range_i = (bins < nb2).astype(jnp.int32)
        zero_i = (mtype2 == 1).astype(jnp.int32)
        nan_i = (mtype2 == 2).astype(jnp.int32)
        two_scan_i = ((nb2 > 2) & (mtype2 != 0)).astype(jnp.int32)
        cnt_bin = jnp.floor(hh * cnt_factor + 0.5) * in_range_i

        at_dflt_i = (bins == dflt2).astype(jnp.int32)
        mf = in_range_i * (1 - zero_i * at_dflt_i)
        bmax = nb2 - 1 - nan_i * two_scan_i
        mr = (in_range_i * (1 - two_scan_i * zero_i * at_dflt_i) *
              (bins <= bmax).astype(jnp.int32))

        mf_f = mf.astype(jnp.float32)
        mr_f = mr.astype(jnp.float32)
        stacked = jnp.concatenate([
            hg * mf_f, hh * mf_f, cnt_bin * mf_f,
            hg * mr_f, hh * mr_f, cnt_bin * mr_f], axis=0)  # (12F, BF)
        tri = (jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 0) <=
               jax.lax.broadcasted_iota(jnp.int32, (BF, BF), 1)
               ).astype(jnp.float32)
        cs = jax.lax.dot_general(
            stacked, tri, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (12F, BF)

        lg_f = cs[0:F2]
        lh_f = cs[F2:2 * F2] + K_EPSILON
        lc_f = cs[2 * F2:3 * F2]
        rg_f = sum_g - lg_f
        rh_f = sum_h_tot - lh_f
        rc_f = num_data - lc_f

        cg_r = cs[3 * F2:4 * F2]
        ch_r = cs[4 * F2:5 * F2]
        cc_r = cs[5 * F2:6 * F2]
        tot_g = jnp.sum(hg * mr_f, axis=1, keepdims=True)
        tot_h = jnp.sum(hh * mr_f, axis=1, keepdims=True)
        tot_c = jnp.sum(cnt_bin * mr_f, axis=1, keepdims=True)
        rg_r = tot_g - cg_r
        rh_r = tot_h - ch_r + K_EPSILON
        rc_r = tot_c - cc_r
        lg_r = sum_g - rg_r
        lh_r = sum_h_tot - rh_r
        lc_r = num_data - rc_r

        gain_f = leaf_gain(lg_f, lh_f) + leaf_gain(rg_f, rh_f)
        gain_r = leaf_gain(lg_r, lh_r) + leaf_gain(rg_r, rh_r)

        gain_shift = leaf_gain(sum_g, sum_h_tot)           # (2F, 1)
        mgs = gain_shift + min_gain_to_split
        mdl = jnp.float32(min_data_in_leaf)

        def cvalid(lc, rc, lh, rh):
            return ((lc >= mdl).astype(jnp.int32) *
                    (rc >= mdl).astype(jnp.int32) *
                    (lh >= min_sum_hessian).astype(jnp.int32) *
                    (rh >= min_sum_hessian).astype(jnp.int32))

        valid_f = (two_scan_i * in_range_i *
                   (bins <= nb2 - 2).astype(jnp.int32) *
                   (1 - zero_i * at_dflt_i) *
                   cvalid(lc_f, rc_f, lh_f, rh_f) *
                   (gain_f > mgs).astype(jnp.int32) * fmask2)
        valid_r = (in_range_i * (bins <= bmax - 1).astype(jnp.int32) *
                   (1 - two_scan_i * zero_i *
                    (bins == dflt2 - 1).astype(jnp.int32)) *
                   cvalid(lc_r, rc_r, lh_r, rh_r) *
                   (gain_r > mgs).astype(jnp.int32) * fmask2)
        if max_depth > 0:
            depth_ok = (depth < max_depth).astype(jnp.int32)
            valid_f = valid_f * depth_ok
            valid_r = valid_r * depth_ok

        gf = jnp.where(valid_f != 0, gain_f, NEG)
        gr = jnp.where(valid_r != 0, gain_r, NEG)

        # preference keys: feature-major, reverse-desc then forward-asc
        feat = jax.lax.broadcasted_iota(jnp.int32, (F2, BF), 0)
        feat = jnp.where(feat >= F, feat - F, feat)
        pref_r = feat * (2 * BF) + (BF - 1 - bins)
        pref_f = feat * (2 * BF) + BF + bins

        out_rows = []
        for c in range(2):
            s = slice(c * F, (c + 1) * F)
            gmax = jnp.maximum(jnp.max(gf[s]), jnp.max(gr[s]))
            key_r = jnp.where(gr[s] >= gmax, pref_r[s], jnp.int32(1 << 30))
            key_f = jnp.where(gf[s] >= gmax, pref_f[s], jnp.int32(1 << 30))
            win = jnp.minimum(jnp.min(key_r), jnp.min(key_f))
            is_rev = (win % (2 * BF)) < BF
            sel_r = (key_r == win).astype(jnp.float32)
            sel_f = (key_f == win).astype(jnp.float32)

            def pick(a_r, a_f):
                return (jnp.sum(a_r[s] * sel_r) + jnp.sum(a_f[s] * sel_f))

            lg = pick(lg_r, lg_f)
            lh = pick(lh_r, lh_f)
            lc = pick(lc_r, lc_f)
            snan = pick((two_scan_i == 0).astype(jnp.float32) *
                        nan_i.astype(jnp.float32) *
                        jnp.ones((F2, BF), jnp.float32),
                        jnp.zeros((F2, BF), jnp.float32))
            wfeat = win // (2 * BF)
            r = win - wfeat * (2 * BF)
            thr = jnp.where(is_rev, BF - 1 - r, r - BF)
            dl = jnp.where(is_rev, jnp.where(snan > 0, 0.0, 1.0), 0.0)

            sg_c = li[c, 0]
            sh_c = li[c, 1] + 2 * K_EPSILON
            nd_c = li[c, 2]
            rg = sg_c - lg
            rh = sh_c - lh
            rc = nd_c - lc
            g_best = jnp.maximum(gmax, NEG)
            gain_rel = jnp.where(g_best > NEG,
                                 g_best - (leaf_gain(sg_c, sh_c) +
                                           min_gain_to_split), NEG)
            row = [
                gain_rel,
                jax.lax.bitcast_convert_type(wfeat, jnp.float32),
                jax.lax.bitcast_convert_type(thr, jnp.float32),
                dl,
                jax.lax.bitcast_convert_type(lc.astype(jnp.int32),
                                             jnp.float32),
                jax.lax.bitcast_convert_type(rc.astype(jnp.int32),
                                             jnp.float32),
                lg, lh - K_EPSILON, rg, rh - K_EPSILON,
                leaf_out(lg, lh), leaf_out(rg, rh),
                jnp.float32(0.0),          # is_cat: numerical only
            ]
            out_rows.append(row)

        lanes = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
        rows = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
        acc = jnp.zeros((8, 128), jnp.float32)
        for c in range(2):
            for k, v in enumerate(out_rows[c]):
                acc = jnp.where((rows == c) & (lanes == k),
                                v, acc)
        out[:] = acc

    out = jax.jit(lambda *a: pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 0 +
                 [pl.BlockSpec((hist_g.shape), lambda: (0, 0)),
                  pl.BlockSpec((hist_h.shape), lambda: (0, 0)),
                  pl.BlockSpec((fmeta.shape), lambda: (0, 0)),
                  pl.BlockSpec((leafinfo.shape), lambda: (0, 0)),
                  pl.BlockSpec((feature_mask.shape), lambda: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda: (0, 0)),
    )(*a))(hist_g, hist_h, fmeta, leafinfo, feature_mask)
    return out
