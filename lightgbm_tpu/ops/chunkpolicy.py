"""Leaf-size-adaptive chunk policy for the histogram/partition passes.

The tree learner processes every per-leaf pass (histogram build, leaf
partition, mega-kernel both-children histogram) in fixed-size row
chunks (``tpu_row_chunk``).  The chunk loop's trip count is dynamic —
all-padding chunks are never executed — but the LAST (often only)
chunk still pays the full chunk width regardless of how few live rows
the leaf holds: at ``num_leaves=255`` and the 4096-row default almost
every split processes a full 4096-row chunk for a leaf of a few dozen
rows.  PERF.md round 12 measured this padded-chunk compute at **68%**
of the training iteration on the 2-core CPU host.

This module picks the chunk width *per pass, per leaf* from a bounded
static menu (<= 4 power-of-two sizes, seeded by ``tpu_row_chunk``):

* a leaf whose live rows fit ONE chunk of a smaller menu width runs
  that width's separately-traced pass variant instead of the base
  grid;
* larger leaves stay on the base grid — multi-chunk processing must
  reproduce the fixed grid's chunk boundaries exactly, because the
  partition's right-side row order depends on them.

Band dispatch is **branch-free**: every width's pass is wrapped in a
``fori_loop`` whose trip count is 0 unless that band is selected.
``lax.switch``/``lax.cond`` would force whole-buffer copies of the
multi-MB row buffers per split (measured — the round-1 conditional
pathology); zero-trip loops skip at runtime and their carries alias in
place, which the tree build already relies on everywhere.

Bit-identity contract (``tpu_chunk_policy=adaptive`` trains trees
bit-identical to ``fixed``):

* **Partition** — a single-window compaction at ANY width W >= cnt
  produces byte-identical buffers to the base grid's single chunk:
  the move is an integer sort + gather (exact), lefts pack forward
  and rights land at ``[start+nl, start+cnt)`` in encounter order in
  both forms, and writes are masked to the live rows.
* **Histogram** — a single chunk of width W accumulates the same live
  rows plus exactly-zero masked padding terms.  Adding exact zeros
  never changes an f32 sum, but XLA's dot reduction STRATEGY changes
  with the contraction length: measured on this stack, widths <= 256
  reduce the live prefix identically to the 4096-wide oracle while
  512/1024 diverge from ~266 live rows up.  Histogram bands are
  therefore capped at ``HIST_EXACT_MAX`` (the e2e matrix in
  tests/test_chunkpolicy.py pins the equivalence; quantized integer
  carriers are exact at any width by construction).

``tpu_row_chunk=auto`` / ``tpu_chunk_policy=auto`` consult the PR-11
``BENCH_history.jsonl`` trajectory first: an ``ab_bench --chunk``
sweep records the winning base width and the measured adaptive
speedup under the host/shape fingerprint (obs/regress.py), and a
same-fingerprint entry overrides the static heuristics below.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# Smaller menu widths considered below the base width (descending).
# The menu is the base width plus every entry strictly below it, capped
# at 4 sizes total.
MENU_LADDER = (1024, 256, 64)

# Histogram passes only band down to widths whose dot-reduction order
# is bit-identical to the base contraction (see module docstring);
# partition passes may use every menu width (integer-exact).
HIST_EXACT_MAX = 256

# default base width when nothing measured says otherwise
# (PERF.md round 3: best end-to-end on v5e at equal slope)
DEFAULT_ROW_CHUNK = 4096

# trajectory tool name the ab_bench --chunk sweep records its winner
# under; resolve() only trusts same-fingerprint entries of this tool
SWEEP_TOOL = "chunk_sweep"

__all__ = [
    "ChunkPolicy", "DEFAULT_ROW_CHUNK", "HIST_EXACT_MAX", "MENU_LADDER",
    "SWEEP_TOOL", "consult_history", "note_variant", "parse_row_chunk",
    "resolve", "resolve_base", "reset_variant_log", "sweep_fingerprint",
    "variant_log", "waste_stats",
]


# ---------------------------------------------------------------------------
# traced-variant registry: every time a (pass, width) variant is built
# into a traced program the learner notes it here, so tests and the
# jaxlint tier-B ``chunk.adaptive`` budget can pin the compiled-variant
# count to the menu — the training-side analog of the serving engine's
# per-(kind, bucket) compile-count keys.
# ---------------------------------------------------------------------------
_VARIANT_LOG: Dict[Tuple[str, int], int] = {}


def note_variant(pass_name: str, width: int) -> None:
    key = (str(pass_name), int(width))
    _VARIANT_LOG[key] = _VARIANT_LOG.get(key, 0) + 1


def variant_log() -> Dict[Tuple[str, int], int]:
    return dict(_VARIANT_LOG)


def reset_variant_log() -> None:
    _VARIANT_LOG.clear()


# ---------------------------------------------------------------------------
# policy object
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class ChunkPolicy:
    """Static per-learner chunk plan.

    ``sizes`` is the full menu (base first, strictly descending);
    ``hist_sizes`` the subset the histogram passes may band to.  With
    ``adaptive=False`` (or a single-entry menu) every pass runs the
    base grid and the learner's lowering is unchanged.
    """

    base: int
    adaptive: bool = False
    sizes: Tuple[int, ...] = field(default=None)  # type: ignore[assignment]
    hist_sizes: Tuple[int, ...] = field(default=None)  # type: ignore[assignment]

    def __post_init__(self):
        base = int(self.base)
        if base <= 0:
            raise ValueError(f"chunk base must be positive, got {base}")
        sizes = (base,) + tuple(w for w in MENU_LADDER if w < base)
        sizes = sizes[:4]
        hist = (base,) + tuple(w for w in sizes[1:] if w <= HIST_EXACT_MAX)
        object.__setattr__(self, "sizes", sizes)
        object.__setattr__(self, "hist_sizes", hist)

    # -- traced helpers -------------------------------------------------
    def band(self, cnt, sizes: Tuple[int, ...]):
        """Traced band index into ``sizes`` (descending): the smallest
        width covering ``cnt`` in one chunk; 0 (the base grid) when
        none does."""
        import jax.numpy as jnp
        idx = jnp.int32(0)
        for w in sizes[1:]:
            idx = idx + (cnt <= w).astype(jnp.int32)
        return idx

    def small_trips(self, cnt, sizes: Tuple[int, ...]):
        """Per-small-width trip counts (0 or 1): entry i-1 gates the
        ``sizes[i]`` variant.  Empty leaves run nothing."""
        import jax.numpy as jnp
        band = self.band(cnt, sizes)
        live = cnt > 0
        return [((band == i) & live).astype(jnp.int32)
                for i in range(1, len(sizes))]

    def base_cover(self, cnt, sizes: Tuple[int, ...]):
        """Base-grid chunk count covering ``cnt`` — zero when a smaller
        band handles the leaf (the all-padding chunks the fixed grid
        would still execute are skipped outright)."""
        import jax.numpy as jnp
        n = (cnt + self.base - 1) // self.base
        if not self.adaptive or len(sizes) < 2:
            return n
        return jnp.where(self.band(cnt, sizes) == 0, n, 0)

    # -- host-side helpers ----------------------------------------------
    def band_of(self, cnt: int, sizes: Optional[Tuple[int, ...]] = None
                ) -> int:
        sizes = sizes or self.sizes
        if not self.adaptive:
            return 0
        idx = 0
        for i, w in enumerate(sizes[1:], 1):
            if cnt <= w:
                idx = i
        return idx

    def padded_rows(self, cnt: int,
                    sizes: Optional[Tuple[int, ...]] = None) -> int:
        """Rows one pass actually processes for a leaf of ``cnt`` live
        rows under this policy (``sizes`` picks the pass menu: the
        full partition menu by default, ``hist_sizes`` for the
        exactness-capped histogram bands)."""
        if cnt <= 0:
            return 0
        sizes = sizes or self.sizes
        w = sizes[self.band_of(cnt, sizes)]
        return -(-cnt // w) * w


def parse_row_chunk(spec) -> Optional[int]:
    """``tpu_row_chunk`` accepts an integer or ``auto`` (consult the
    measured trajectory, then the static default).  Returns None for
    auto."""
    s = str(spec).strip().lower()
    if s in ("auto", ""):
        return None
    try:
        # int(float(.)) matches the int-param coercion this knob had
        # before it learned "auto" (sklearn grids pass 4096.0)
        v = int(float(s))
    except ValueError:
        raise ValueError(
            f"tpu_row_chunk must be 'auto' or a positive integer, "
            f"got {spec!r}")
    if v <= 0:
        raise ValueError(f"tpu_row_chunk must be positive, got {v}")
    return v


# ---------------------------------------------------------------------------
# trajectory consult (ROADMAP item 7 slice): the ab_bench --chunk sweep
# records its winner keyed by the host/shape fingerprint; auto modes
# trust a same-fingerprint entry over the static heuristics.
# ---------------------------------------------------------------------------
def sweep_fingerprint(rows: Optional[int], features: Optional[int]
                      ) -> Dict[str, Any]:
    """The fingerprint chunk-sweep entries are keyed by: hardware +
    shape band only.  Deliberately knob-free — the sweep's JOB is to
    choose the knob, so the knob must not fork its series."""
    from ..obs import regress
    return regress.fingerprint(config={}, rows=rows, features=features)


# (path, mtime, size) -> parsed entries: learner/dataset construction
# consults per Booster under the default auto modes, and re-parsing a
# growing committed trajectory per fold would be O(folds x file size)
_HISTORY_CACHE: Dict[str, Any] = {}


def _read_history_cached(path: Optional[str]):
    import os

    from ..obs import regress
    real = path or regress.default_path()
    try:
        st = os.stat(real)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        stamp = None
    if (_HISTORY_CACHE.get("path") == real
            and _HISTORY_CACHE.get("stamp") == stamp):
        return _HISTORY_CACHE["entries"]
    entries, _ = regress.read_history(real)
    _HISTORY_CACHE.update(path=real, stamp=stamp, entries=entries)
    return entries


def consult_history(rows: Optional[int], features: Optional[int],
                    path: Optional[str] = None) -> Dict[str, Any]:
    """Latest same-fingerprint ``chunk_sweep`` verdict, or {}.

    Recognized metrics: ``best_row_chunk`` (the sweep's winning base
    width) and ``adaptive_speedup`` (fixed/adaptive wall ratio; > 1
    means adaptive won on this hardware/shape)."""
    from ..obs import regress
    try:
        key = regress.fingerprint_key(sweep_fingerprint(rows, features))
        entries = _read_history_cached(path)
    except Exception:
        return {}
    out: Dict[str, Any] = {}
    for e in entries:
        if e.get("aborted") or e.get("tool") != SWEEP_TOOL:
            continue
        if e.get("fingerprint_key") != key:
            continue
        m = e.get("metrics") or {}
        if "best_row_chunk" in m:
            out["best_row_chunk"] = int(m["best_row_chunk"])
        if "adaptive_speedup" in m:
            out["adaptive_speedup"] = float(m["adaptive_speedup"])
    return out


def resolve_base(config, rows: Optional[int] = None,
                 features: Optional[int] = None) -> int:
    """Uncapped base chunk width: the explicit ``tpu_row_chunk`` value,
    or — under ``auto`` — a same-fingerprint chunk-sweep winner from
    the trajectory, else the static default.  Dataset construction and
    the learner both resolve through here so the streamed ingest
    geometry matches the training geometry."""
    spec = parse_row_chunk(getattr(config, "tpu_row_chunk",
                                   DEFAULT_ROW_CHUNK))
    if spec is None:
        spec = int(consult_history(rows, features).get(
            "best_row_chunk", DEFAULT_ROW_CHUNK))
    return spec


def resolve(config, num_data: int, num_leaves: int,
            eligible: bool, base: int,
            features: Optional[int] = None) -> Tuple[int, "ChunkPolicy"]:
    """(base row chunk, policy) for one learner.

    ``base`` is the learner's ALREADY-derived chunk width (it owns the
    pow2/geometry caps — one derivation site, so ``policy.base`` can
    never drift from the grid the partition loops stride).
    ``eligible`` gates the adaptive mode: the caller owns the path
    checks (plain XLA hist/partition, serial mode, f32 hist dtype, no
    in-context doubling).
    """
    mode = str(getattr(config, "tpu_chunk_policy", "auto")
               or "auto").strip().lower()
    if mode not in ("auto", "fixed", "adaptive"):
        mode = "auto"      # Config._post_process already warned
    if mode == "fixed" or not eligible:
        if mode == "adaptive":
            from ..utils import log
            log.warning(
                "tpu_chunk_policy=adaptive needs the plain XLA serial "
                "tree path (no Pallas hist/partition/mega kernels, "
                "parallel learners, tpu_ab_double or non-f32 hist "
                "dtype); using the fixed grid")
        return base, ChunkPolicy(base, adaptive=False)
    if mode == "auto":
        verdict = consult_history(num_data, features)
        speed = verdict.get("adaptive_speedup")
        if speed is not None:
            adaptive = speed > 1.0
        else:
            # small-leaf-regime heuristic: adaptive pays when the
            # fixed grid's worst case (one base chunk per split)
            # exceeds the data actually touched per tree level —
            # i.e. when the average leaf is smaller than the chunk
            adaptive = max(num_leaves - 1, 1) * base > num_data
    else:
        adaptive = True
    policy = ChunkPolicy(base, adaptive=adaptive)
    if len(policy.sizes) < 2:
        policy = ChunkPolicy(base, adaptive=False)
    return base, policy


# ---------------------------------------------------------------------------
# padding-waste accounting (telemetry: train.chunk.* gauges)
# ---------------------------------------------------------------------------
def waste_stats(leaf_counts, policy: "ChunkPolicy") -> Dict[str, float]:
    """Per-band occupancy + padding-waste ratio of one tree's leaves
    (host ints — called at tree materialization time with values the
    trainer already has; zero device ops).

    ``waste`` is the fraction of processed rows that were padding
    under ``policy``, accounting BOTH pass families — the partition
    (full menu) and the exactness-capped histogram bands
    (``hist_sizes``; leaves in the 256..base gap still pay a full
    base-width histogram chunk and the gauge must not hide it);
    ``fixed_waste`` is the same for the base-only grid, so the pair
    shows what the adaptive bands actually saved.  Per-band occupancy
    is the partition-window view (one leaf = one selected width)."""
    live = 0
    part_padded = 0
    hist_padded = 0
    fixed_padded = 0
    per_band: Dict[int, Dict[str, float]] = {}
    fixed = ChunkPolicy(policy.base, adaptive=False)
    for cnt in leaf_counts:
        cnt = int(cnt)
        if cnt <= 0:
            continue
        live += cnt
        part_padded += policy.padded_rows(cnt)
        hist_padded += policy.padded_rows(cnt, policy.hist_sizes)
        fixed_padded += 2 * fixed.padded_rows(cnt)
        w = policy.sizes[policy.band_of(cnt)]
        b = per_band.setdefault(w, {"leaves": 0, "rows": 0, "padded": 0})
        b["leaves"] += 1
        b["rows"] += cnt
        b["padded"] += policy.padded_rows(cnt)
    padded = part_padded + hist_padded
    out: Dict[str, float] = {
        "live_rows": float(live),
        "padded_rows": float(padded),
        "waste": 1.0 - 2 * live / padded if padded else 0.0,
        "fixed_waste": (1.0 - 2 * live / fixed_padded
                        if fixed_padded else 0.0),
    }
    for w, b in sorted(per_band.items()):
        band = f"band_{1 << int(math.log2(w)):d}" if w else "band_0"
        out[f"{band}.leaves"] = float(b["leaves"])
        out[f"{band}.occupancy"] = (b["rows"] / b["padded"]
                                    if b["padded"] else 0.0)
    return out
