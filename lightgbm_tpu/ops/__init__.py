"""Subpackage init."""
