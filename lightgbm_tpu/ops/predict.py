"""On-device tree traversal over binned data.

TPU-native equivalent of Tree::AddPredictionToScore on binned data
(reference: include/LightGBM/tree.h:133-140, src/io/cuda/cuda_tree.cu):
all rows advance one level per step of a while_loop; finished rows hold their
(negative) leaf reference.  The loop runs ~tree-depth iterations, fully
vectorized across rows.

``predict_leaf_thridx`` runs the same loop for LOADED models (real-valued
thresholds, no bin mappers): the host converts raw values to per-feature
THRESHOLD-INDEX space with exact float64 searchsorted (v <= t_k iff
#thresholds-below-v <= k), so the device compares integers and the f64
decision semantics of the host walk (tree.py predict_leaf) are preserved
bit-for-bit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .partition import split_decision


def predict_leaf_thridx(packed_vals: jnp.ndarray, node: dict) -> jnp.ndarray:
    """Leaf index per row for a loaded (real-threshold) tree.

    Args:
      packed_vals: (Fu, n) i32 — per (used-feature, row): b*4 + nan*2 +
        zeroish, where b = #thresholds(feature) strictly below the value,
        nan = isnan(raw), zeroish = |effective value| <= kZeroThreshold
        (after the NaN->0 substitution the host walk applies for
        non-NaN-missing nodes).
      node: per-internal-node arrays: 'col' (index into the used-feature
        enumeration), 'kidx' (threshold index), 'default_left', 'mtype',
        'left', 'right' (children <0 = ~leaf), 'b0' (F,) threshold index
        of value 0.0 per feature, scalar 'num_nodes'.
    """
    n = packed_vals.shape[1]
    cur = jnp.zeros((n,), dtype=jnp.int32)
    f_iota = jax.lax.broadcasted_iota(jnp.int32, packed_vals.shape, 0)
    packed_nodes = jnp.stack([
        node["col"], node["kidx"], node["default_left"].astype(jnp.int32),
        node["mtype"], node["left"], node["right"],
        jnp.take(node["b0"], node["col"])], axis=0).astype(jnp.int32)

    def empty(_):
        return jnp.zeros((n,), dtype=jnp.int32)

    def run(_):
        def cond(c):
            return jnp.any(c >= 0)

        def body(c):
            active = c >= 0
            nid = jnp.maximum(c, 0)
            rows = jnp.take(packed_nodes, nid, axis=1)       # (7, n)
            col, kidx, dleft, mtype, left, right, b0 = (
                rows[0], rows[1], rows[2], rows[3], rows[4], rows[5],
                rows[6])
            pv = jnp.sum(jnp.where(f_iota == col[None, :], packed_vals, 0),
                         axis=0)
            b = pv >> 2
            is_nan = (pv & 2) != 0
            zeroish = (pv & 1) != 0
            # NaN substitutes 0.0 unless the node is NaN-missing
            b_eff = jnp.where(is_nan & (mtype != 2), b0, b)
            missing = jnp.where(mtype == 2, is_nan,
                                (mtype == 1) & zeroish)
            goes_left = jnp.where(missing, dleft != 0, b_eff <= kidx)
            nxt = jnp.where(goes_left, left, right)
            # see predict_leaf_binned: vmapped cond runs this branch for
            # empty trees too; terminate them on leaf 0 (the loaded
            # pack's -1-initialized children already do, but a zero-
            # node tree whose arrays were stacked differently must not
            # hang the whole forest's while loop)
            nxt = jnp.where(node["num_nodes"] > 0, nxt, jnp.int32(-1))
            return jnp.where(active, nxt, c)

        final = jax.lax.while_loop(cond, body, cur)
        return -(final + 1)

    return jax.lax.cond(node["num_nodes"] > 0, run, empty, operand=None)


def predict_leaf_binned(binned: jnp.ndarray, node: dict,
                        num_nodes_limit: int | None = None) -> jnp.ndarray:
    """Return the leaf index for every row of a binned matrix.

    Args:
      binned: (N, G) integer group-bin matrix.
      node: device dict with per-internal-node arrays (shape (L-1,)):
        'col', 'bin_start', 'is_bundled', 'num_bin', 'default_bin',
        'missing_type', 'threshold', 'default_left', 'left', 'right'
        (children: >=0 internal node id, <0 encoded leaf ~leaf_id),
        plus scalar 'num_nodes'.
    """
    # rows on the LANE axis: the per-row column read becomes a masked
    # reduction over G (a per-row take_along_axis over a few-lane axis
    # runs ~400x slower on TPU — same pathology as the partition's
    # split-column read, see PERF.md)
    return predict_leaf_binned_t(binned.T, node, num_nodes_limit)


def predict_leaf_binned_t(binned_t: jnp.ndarray, node: dict,
                          num_nodes_limit: int | None = None) -> jnp.ndarray:
    """``predict_leaf_binned`` over an already-transposed (G, n) matrix.

    This is the layout the fused trainer keeps resident (``part_bins``
    sans padding), so train-set traversal can read the live carrier
    directly instead of materializing a row-major second copy.
    """
    n = binned_t.shape[1]
    num_nodes = node["num_nodes"]
    cur = jnp.zeros((n,), dtype=jnp.int32)
    binned_t = binned_t.astype(jnp.int32)            # (G, n)
    g_iota = jax.lax.broadcasted_iota(jnp.int32, binned_t.shape, 0)

    # ALL per-node scalars ride ONE packed matrix so each level costs a
    # single lane-axis gather (the partition's proven-fast pattern —
    # nodes on the LANE axis, fields on sublanes): ten separate 1-D
    # gathers from the tiny node arrays serialize on TPU (~12 s for
    # 1M rows x a deep tree, measured)
    packed = jnp.stack([
        node["col"], node["bin_start"], node["is_bundled"],
        node["num_bin"], node["default_bin"], node["missing_type"],
        node["threshold"], node["default_left"].astype(jnp.int32),
        node["left"], node["right"]]
        + ([node["is_cat"].astype(jnp.int32)] if "is_cat" in node else []),
        axis=0).astype(jnp.int32)                     # (K, nodes)

    # empty tree (single leaf): everything is leaf 0
    def empty(_):
        return jnp.full((n,), 0, dtype=jnp.int32)

    def run(_):
        def cond(state):
            c = state
            return jnp.any(c >= 0)

        def body(state):
            c = state
            active = c >= 0
            nid = jnp.maximum(c, 0)
            rows = jnp.take(packed, nid, axis=1)      # (K, n) lane gather
            (col, bin_start, is_bundled, nb, default_bin, missing_type,
             threshold, default_left, left, right) = (
                rows[0], rows[1], rows[2], rows[3], rows[4],
                rows[5], rows[6], rows[7], rows[8], rows[9])
            gb = jnp.sum(jnp.where(g_iota == col[None, :], binned_t, 0),
                         axis=0)
            # bundled features: recover the feature-local bin
            fb_raw = gb - bin_start
            in_range = (fb_raw >= 1) & (fb_raw <= nb - 1)
            fb = jnp.where(is_bundled == 1,
                           jnp.where(in_range, fb_raw, default_bin), gb)
            goes_left = split_decision(
                fb, threshold, default_left == 1, missing_type,
                default_bin, nb - 1)
            if "is_cat" in node:
                # categorical: membership of fb in the node's category
                # set.  Out-of-range bins (the prediction-path OOV
                # sentinel num_bin — see BinMapper.values_to_bins — whose
                # take_along_axis read would clip onto a REAL bin) fail
                # membership explicitly and fall right, the reference's
                # CategoricalDecision behavior for unseen categories.
                cat_rows = jnp.take(node["cat_set"], nid, axis=0)
                member = jnp.take_along_axis(
                    cat_rows, jnp.minimum(fb, cat_rows.shape[1] - 1)[:, None],
                    axis=1)[:, 0]
                member = member & (fb <= nb - 1)
                goes_left = jnp.where(rows[10] == 1, member, goes_left)
            nxt = jnp.where(goes_left, left, right)
            # empty tree: land on leaf 0 immediately.  The num_nodes>0
            # cond below short-circuits the plain call, but under vmap
            # (the serving engine's stacked forests) cond lowers to a
            # select that RUNS this branch for every tree — an empty
            # tree's slot-0 children point back at node 0 and the while
            # loop would never terminate for the whole batch.
            nxt = jnp.where(num_nodes > 0, nxt, jnp.int32(-1))
            return jnp.where(active, nxt, c)

        final = jax.lax.while_loop(cond, body, cur)
        return -(final + 1)  # decode ~leaf

    return jax.lax.cond(num_nodes > 0, run, empty, operand=None)


def linear_leaf_values(raw_aug: jnp.ndarray, leaf: jnp.ndarray,
                       const: jnp.ndarray, coeff: jnp.ndarray,
                       fid: jnp.ndarray,
                       fallback: jnp.ndarray) -> jnp.ndarray:
    """(n,) piece-wise-linear leaf outputs for ONE tree (reference:
    tree.cpp PredictLinear): ``const[leaf] + Σ_j coeff[leaf, j] *
    raw_aug[row, fid[leaf, j]]``, with rows carrying NaN in ANY of the
    leaf's regressors falling back to the constant ``fallback[leaf]``.

    ``raw_aug`` is the raw feature matrix with ONE all-zero column
    appended: unused coefficient slots point their ``fid`` at it, so the
    gather stays rectangular (no per-leaf feature counts), the padded
    terms add exact zeros, and — because the sentinel column is never
    NaN — the NaN test reduces over exactly the leaf's real regressors.
    Non-linear leaves are encoded as all-sentinel rows with
    ``const = leaf_value``, so one FMA serves mixed forests."""
    c = jnp.take(const, leaf)                        # (n,)
    fb = jnp.take(fallback, leaf)
    cf = jnp.take(coeff, leaf, axis=0)               # (n, J)
    ff = jnp.take(fid, leaf, axis=0)                 # (n, J)
    x = jnp.take_along_axis(raw_aug, ff, axis=1)     # (n, J)
    bad = jnp.any(jnp.isnan(x), axis=1)
    lin = c + jnp.sum(cf * jnp.where(jnp.isnan(x), jnp.float32(0.0), x),
                      axis=1)
    return jnp.where(bad, fb, lin)
