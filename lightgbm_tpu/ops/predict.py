"""On-device tree traversal over binned data.

TPU-native equivalent of Tree::AddPredictionToScore on binned data
(reference: include/LightGBM/tree.h:133-140, src/io/cuda/cuda_tree.cu):
all rows advance one level per step of a while_loop; finished rows hold their
(negative) leaf reference.  The loop runs ~tree-depth iterations, fully
vectorized across rows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .partition import split_decision


def predict_leaf_binned(binned: jnp.ndarray, node: dict,
                        num_nodes_limit: int | None = None) -> jnp.ndarray:
    """Return the leaf index for every row of a binned matrix.

    Args:
      binned: (N, G) integer group-bin matrix.
      node: device dict with per-internal-node arrays (shape (L-1,)):
        'col', 'bin_start', 'is_bundled', 'num_bin', 'default_bin',
        'missing_type', 'threshold', 'default_left', 'left', 'right'
        (children: >=0 internal node id, <0 encoded leaf ~leaf_id),
        plus scalar 'num_nodes'.
    """
    n = binned.shape[0]
    num_nodes = node["num_nodes"]
    cur = jnp.zeros((n,), dtype=jnp.int32)
    # rows on the LANE axis: the per-row column read becomes a masked
    # reduction over G (a per-row take_along_axis over a few-lane axis
    # runs ~400x slower on TPU — same pathology as the partition's
    # split-column read, see PERF.md)
    binned_t = binned.T.astype(jnp.int32)            # (G, n)
    g_iota = jax.lax.broadcasted_iota(jnp.int32, binned_t.shape, 0)

    # empty tree (single leaf): everything is leaf 0
    def empty(_):
        return jnp.full((n,), 0, dtype=jnp.int32)

    def run(_):
        def cond(state):
            c = state
            return jnp.any(c >= 0)

        def body(state):
            c = state
            active = c >= 0
            nid = jnp.maximum(c, 0)
            col = node["col"][nid]
            gb = jnp.sum(jnp.where(g_iota == col[None, :], binned_t, 0),
                         axis=0)
            # bundled features: recover the feature-local bin
            fb_raw = gb - node["bin_start"][nid]
            nb = node["num_bin"][nid]
            in_range = (fb_raw >= 1) & (fb_raw <= nb - 1)
            fb = jnp.where(node["is_bundled"][nid] == 1,
                           jnp.where(in_range, fb_raw, node["default_bin"][nid]),
                           gb)
            goes_left = split_decision(
                fb, node["threshold"][nid], node["default_left"][nid],
                node["missing_type"][nid], node["default_bin"][nid], nb - 1)
            if "is_cat" in node:
                # categorical: membership of fb in the node's category set
                cat_rows = node["cat_set"][nid]            # (n, BF) row gather
                member = jnp.take_along_axis(
                    cat_rows, fb[:, None], axis=1)[:, 0]
                goes_left = jnp.where(node["is_cat"][nid], member, goes_left)
            nxt = jnp.where(goes_left, node["left"][nid], node["right"][nid])
            return jnp.where(active, nxt, c)

        final = jax.lax.while_loop(cond, body, cur)
        return -(final + 1)  # decode ~leaf

    return jax.lax.cond(num_nodes > 0, run, empty, operand=None)
