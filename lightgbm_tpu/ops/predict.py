"""On-device tree traversal over binned data.

TPU-native equivalent of Tree::AddPredictionToScore on binned data
(reference: include/LightGBM/tree.h:133-140, src/io/cuda/cuda_tree.cu):
all rows advance one level per step of a while_loop; finished rows hold their
(negative) leaf reference.  The loop runs ~tree-depth iterations, fully
vectorized across rows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .partition import split_decision


def predict_leaf_binned(binned: jnp.ndarray, node: dict,
                        num_nodes_limit: int | None = None) -> jnp.ndarray:
    """Return the leaf index for every row of a binned matrix.

    Args:
      binned: (N, G) integer group-bin matrix.
      node: device dict with per-internal-node arrays (shape (L-1,)):
        'col', 'bin_start', 'is_bundled', 'num_bin', 'default_bin',
        'missing_type', 'threshold', 'default_left', 'left', 'right'
        (children: >=0 internal node id, <0 encoded leaf ~leaf_id),
        plus scalar 'num_nodes'.
    """
    n = binned.shape[0]
    num_nodes = node["num_nodes"]
    cur = jnp.zeros((n,), dtype=jnp.int32)
    # rows on the LANE axis: the per-row column read becomes a masked
    # reduction over G (a per-row take_along_axis over a few-lane axis
    # runs ~400x slower on TPU — same pathology as the partition's
    # split-column read, see PERF.md)
    binned_t = binned.T.astype(jnp.int32)            # (G, n)
    g_iota = jax.lax.broadcasted_iota(jnp.int32, binned_t.shape, 0)

    # ALL per-node scalars ride ONE packed matrix so each level costs a
    # single lane-axis gather (the partition's proven-fast pattern —
    # nodes on the LANE axis, fields on sublanes): ten separate 1-D
    # gathers from the tiny node arrays serialize on TPU (~12 s for
    # 1M rows x a deep tree, measured)
    packed = jnp.stack([
        node["col"], node["bin_start"], node["is_bundled"],
        node["num_bin"], node["default_bin"], node["missing_type"],
        node["threshold"], node["default_left"].astype(jnp.int32),
        node["left"], node["right"]]
        + ([node["is_cat"].astype(jnp.int32)] if "is_cat" in node else []),
        axis=0).astype(jnp.int32)                     # (K, nodes)

    # empty tree (single leaf): everything is leaf 0
    def empty(_):
        return jnp.full((n,), 0, dtype=jnp.int32)

    def run(_):
        def cond(state):
            c = state
            return jnp.any(c >= 0)

        def body(state):
            c = state
            active = c >= 0
            nid = jnp.maximum(c, 0)
            rows = jnp.take(packed, nid, axis=1)      # (K, n) lane gather
            (col, bin_start, is_bundled, nb, default_bin, missing_type,
             threshold, default_left, left, right) = (
                rows[0], rows[1], rows[2], rows[3], rows[4],
                rows[5], rows[6], rows[7], rows[8], rows[9])
            gb = jnp.sum(jnp.where(g_iota == col[None, :], binned_t, 0),
                         axis=0)
            # bundled features: recover the feature-local bin
            fb_raw = gb - bin_start
            in_range = (fb_raw >= 1) & (fb_raw <= nb - 1)
            fb = jnp.where(is_bundled == 1,
                           jnp.where(in_range, fb_raw, default_bin), gb)
            goes_left = split_decision(
                fb, threshold, default_left == 1, missing_type,
                default_bin, nb - 1)
            if "is_cat" in node:
                # categorical: membership of fb in the node's category set
                cat_rows = jnp.take(node["cat_set"], nid, axis=0)
                member = jnp.take_along_axis(
                    cat_rows, fb[:, None], axis=1)[:, 0]
                goes_left = jnp.where(rows[10] == 1, member, goes_left)
            nxt = jnp.where(goes_left, left, right)
            return jnp.where(active, nxt, c)

        final = jax.lax.while_loop(cond, body, cur)
        return -(final + 1)  # decode ~leaf

    return jax.lax.cond(num_nodes > 0, run, empty, operand=None)
