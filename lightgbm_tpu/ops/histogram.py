"""Histogram construction on TPU.

TPU-native replacement for the reference histogram kernels
(src/io/dense_bin.hpp ConstructHistogram, src/treelearner/cuda/
cuda_histogram_constructor.cu): TPUs have no fast scatter-add, so the
(rows x groups) -> (groups x bins) accumulation is reformulated as a one-hot
MXU matmul: for each row chunk, hist[g, b, c] += sum_r (bin[r, g] == b) * gh[r, c].
The one-hot factor is exact in bfloat16/float32 and the contraction runs on the
systolic array; per-chunk partials accumulate in float32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def histogram_leaf(bins_slice: jnp.ndarray, gh_slice: jnp.ndarray,
                   num_bins: int, row_chunk: int = 2048) -> jnp.ndarray:
    """Build the (G, B, 2) grad/hess histogram for one leaf's row slice.

    Args:
      bins_slice: (S, G) integer bins for the leaf's rows (padding rows must
        have their gh zeroed by the caller).
      gh_slice: (S, 2) float32 gradient/hessian pairs (zeros on padding).
      num_bins: padded bin count B (static).
      row_chunk: rows per MXU matmul chunk (static).

    Returns:
      (G, B, 2) float32 histogram.
    """
    S, G = bins_slice.shape
    B = num_bins
    C = min(S, row_chunk)
    n_chunks = (S + C - 1) // C
    pad = n_chunks * C - S
    if pad:
        bins_slice = jnp.pad(bins_slice, ((0, pad), (0, 0)))
        gh_slice = jnp.pad(gh_slice, ((0, pad), (0, 0)))

    bins_c = bins_slice.reshape(n_chunks, C, G)
    gh_c = gh_slice.reshape(n_chunks, C, 2)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (1, B, 1), 1)

    def body(acc, chunk):
        bins_chunk, gh_chunk = chunk
        # (G, B, C) one-hot: exact in f32; contraction over rows on the MXU
        onehot = (bins_chunk.T[:, None, :].astype(jnp.int32) == iota_b)
        partial = jnp.einsum(
            "gbc,cj->gbj", onehot.astype(jnp.float32), gh_chunk,
            preferred_element_type=jnp.float32)
        return acc + partial, None

    if n_chunks == 1:
        onehot = (bins_c[0].T[:, None, :].astype(jnp.int32) == iota_b)
        return jnp.einsum("gbc,cj->gbj", onehot.astype(jnp.float32), gh_c[0],
                          preferred_element_type=jnp.float32)
    acc0 = jnp.zeros((G, B, 2), dtype=jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_c, gh_c))
    return acc


def gather_leaf_rows(binned: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                     indices: jnp.ndarray, start: jnp.ndarray, size: int,
                     count: jnp.ndarray):
    """Slice a leaf's row ids out of the partition array and gather its data.

    ``indices`` is padded so that ``start + size`` never exceeds its length;
    padding entries point at the sentinel row (all-zero gh).  Rows beyond
    ``count`` inside the slice belong to *other* leaves, so their gh is zeroed.

    Returns (bins (size, G), gh (size, 2)).
    """
    idx = jax.lax.dynamic_slice(indices, (start,), (size,))
    pos = jax.lax.iota(jnp.int32, size)
    valid = pos < count
    bins = jnp.take(binned, idx, axis=0)
    g = jnp.take(grad, idx) * valid
    h = jnp.take(hess, idx) * valid
    return bins, jnp.stack([g, h], axis=1)
