"""Histogram construction on TPU.

TPU-native replacement for the reference histogram kernels
(src/io/dense_bin.hpp ConstructHistogram, src/treelearner/cuda/
cuda_histogram_constructor.cu).  TPUs have no fast scatter-add, so the
(rows x groups) -> (groups x bins) accumulation is reformulated as a one-hot
MXU matmul.  Rows are kept *physically partitioned by leaf* (see
models/learner.py), so a leaf's histogram reads one contiguous column slice —
no gathers touch HBM on the hot path.

Row-payload layout is TRANSPOSED: the binned matrix is (G, N_pad) and the
packed (grad, hess, rowid) payload is (3, N_pad), with ROWS ON THE MINOR
(lane) axis.  With the natural (N, G) orientation XLA prefers column-major
for the big buffers (G < 128 lanes would waste 4.5x footprint row-major)
while the partition's row-gather loops demand row-major — the disagreement
materialized as full-buffer transpose copies inside the tree-build while
loop, ~60% of its wall clock.  (G, N) row-major is the same physical bytes
as (N, G) column-major, so every consumer now agrees with the layout XLA
wants and the copies vanish.

Two implementations with identical semantics:
  * ``leaf_hist_slice``  — pure-XLA chunked einsum (runs everywhere; the
    oracle for tests and the CPU path).
  * ``leaf_hist_pallas`` — Pallas TPU kernel that DMAs (G, chunk) tiles
    straight from HBM with a dynamic trip count and accumulates per-feature
    (2, B) partial histograms in VMEM.

The contraction layout batches ``gblock`` feature groups into the matmul N
dimension — out[(j),(g,b)] = sum_c gh[j,c] * (bins[g,c]==b) — because the
left operand (grad/hess) is shared across features.  This keeps the MXU's
N dimension wide instead of the naive per-feature (C,B)@(B,2) shape whose
N=2 wastes 126/128 lanes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def linear_moment_planes(feat_hist, rep_vals):
    """Per-bin linear moment planes (Σx·g, Σx·h, Σx·x·h) of a leaf,
    derived from its already-accumulated feature-view histogram
    (linear_tree_mode=leafwise_gain).

    The naive plan — ride extra weighted columns (x·g, x·h, x²·h) in
    the one-hot MXU matmuls above — is never necessary: the binned
    regressor is a PER-BIN CONSTANT, so within bin b of feature f

        Σ_{i in bin b} x_i·g_i = rep[f, b] · Σ_{i in bin b} g_i
                               = rep[f, b] · hist[f, b, 0]

    and likewise for the h-moments.  The moments are therefore exact
    rank-1 scalings of the (F, BF, 2) histogram by the representative
    value table (ops/binning.py:bin_rep_values) — zero extra matmul
    throughput, zero extra histogram state, and the parent-minus-child
    subtraction trick holds automatically (the derivation is linear in
    the histogram).  ``rep_vals`` is (F, BF) f32 with 0.0 at the
    NaN/zero-missing bins, which is what lets both split-scan
    directions share one set of moment prefix sums (see
    ops/split.py:find_best_split_linear).

    Returns (3, F, BF): [Σx·g, Σx·h, Σx·x·h].
    """
    xg = rep_vals * feat_hist[..., 0]
    xh = rep_vals * feat_hist[..., 1]
    return jnp.stack([xg, xh, rep_vals * xh])


def leaf_hist_slice(part_bins, part_ghi, start, cnt, *,
                    num_bins: int, row_chunk: int,
                    gblock: int = 0, dtype=jnp.float32, vary=lambda x: x,
                    num_groups: int = 0, flat_geom=None, cover=None):
    """(G, B, 2) histogram of the contiguous partitioned rows
    [start, start+cnt) of the (G, N_pad) binned matrix with matching
    (>=2, N_pad) packed (grad, hess, ...) rows; rows beyond ``cnt``
    inside the last chunk are masked via zeroed grad/hess.

    ``cover`` overrides the chunk trip count (the leaf-size-adaptive
    policy passes the cover length — 0 skips the pass outright, which
    is how a zero-trip band variant costs nothing at runtime).

    Digit-decomposed one-hot accumulation: onehot_B(x) factors as
    onehot_hi(x >> 4) (x) onehot_16(x & 15), so the per-chunk histogram is a
    batched (BH*2, C) @ (C, 16) matmul per feature block — one-hot
    GENERATION drops from O(C*B) to O(C*(BH+16)) elements per feature,
    which is what bounds the naive formulation on the VPU (the MXU matmul
    itself streams at full speed either way).  This is the TPU replacement
    for the reference's scalar scatter-adds (dense_bin.hpp
    ConstructHistogram) and CUDA shared-memory atomics
    (cuda_histogram_constructor.cu).
    """
    G, Np = part_bins.shape
    if num_groups:      # buffer may be sublane-padded for the Pallas
        G = num_groups  # partition kernel's DMA tiling; ignore pad rows
    C = row_chunk
    B = num_bins
    BH = (B + 15) // 16          # high-digit cardinality
    Bp = BH * 16
    if gblock <= 0:
        # keep the per-block intermediates in VMEM: the low-digit one-hot is
        # (gblock, C, 16) and the WEIGHTED high-digit buffer is
        # (gblock, C, 2*BH) — budget both
        gblock = max(1, (4 * 1024 * 1024) // (C * (16 + 2 * BH) * 4))
    nblk = (G + gblock - 1) // gblock
    Gp = nblk * gblock
    n_chunks = (cnt + C - 1) // C if cover is None else cover
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (1, 1, BH), 2)
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 16), 2)

    def body(ci, acc):
        row0 = start + ci * C
        bins = jax.lax.dynamic_slice(
            part_bins, (0, row0), (G, C)).astype(jnp.int32)
        gh3 = jax.lax.dynamic_slice(
            part_ghi, (0, row0), (part_ghi.shape[0], C))
        g = gh3[0]
        h = gh3[1]
        if Gp > G:
            bins = jnp.pad(bins, ((0, Gp - G), (0, 0)), constant_values=-1)
        valid = (ci * C + jax.lax.iota(jnp.int32, C)) < cnt
        gv = (g * valid).astype(dtype)[None, :, None]         # (1, C, 1)
        hv = (h * valid).astype(dtype)[None, :, None]
        out = []
        for i in range(nblk):
            blk = bins[i * gblock:(i + 1) * gblock, :]        # (gblk, C)
            hi = blk >> 4
            lo = blk & 15
            m_hi = hi[:, :, None] == iota_hi                  # (gblk, C, BH)
            oh_lo = (lo[:, :, None] == iota_lo).astype(dtype)  # (gblk, C, 16)
            # weighted high-digit one-hots for (grad, hess) side by side,
            # generated DIRECTLY from the comparison mask: materializing
            # the raw f32 oh_hi first costs ~28% of the whole pass
            # (measured; the generation traffic bounds this kernel)
            wg = jnp.concatenate([jnp.where(m_hi, gv, jnp.array(0, dtype)),
                                  jnp.where(m_hi, hv, jnp.array(0, dtype))],
                                 axis=2)
            out.append(jax.lax.dot_general(
                wg, oh_lo,
                dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32))  # (gblk, 2*BH, 16)
        # ONE loop-carried array (a tuple of nblk carries costs nblk
        # body-level fusions per split in the outer tree loop)
        return acc + jnp.stack(out)

    acc = vary(jnp.zeros((nblk, gblock, 2 * BH, 16), jnp.float32))
    acc = jax.lax.fori_loop(0, n_chunks, body, acc)
    per = acc.reshape(Gp, 2 * BH, 16)[:G]               # block-major == G
    per = per.reshape(G, 2, Bp)                         # b = hi*16 + lo
    if flat_geom is not None:
        # (8, WL) lane-flattened (2, Gf, Bf) slot for the Pallas
        # hist-state RMW kernel (ops/hist_state_pallas.py)
        Gf, Bf, WL = flat_geom
        jg = jnp.moveaxis(per, 1, 0)                    # (2, G, Bp)
        jg = jnp.pad(jg, ((0, 0), (0, Gf - G), (0, Bf - Bp)))
        return jg.reshape(8, WL)
    return jnp.moveaxis(per[:, :, :B], 1, 2)            # (G, B, 2)


def leaf_hist_banded(part_bins, part_ghi, start, cnt, *, num_bins: int,
                     policy, dtype=jnp.float32, vary=lambda x: x,
                     num_groups: int = 0):
    """Leaf-size-adaptive histogram (ops/chunkpolicy.py): the base-grid
    pass runs with a cover of 0 when a smaller band covers the leaf,
    and each smaller menu width runs a zero-or-one-trip single-chunk
    variant.  Exactly one variant executes per call; the others skip at
    runtime (dynamic trip counts — no ``lax.switch``, whose branch
    plumbing copies the multi-MB row buffers).

    Bit-identity: the selected small chunk accumulates the same live
    rows plus exactly-zero masked padding, and the band widths are
    capped at ``HIST_EXACT_MAX`` where the dot reduction provably
    groups the live prefix like the base width does (module docstring
    of chunkpolicy).  Summing the per-variant outputs (all-zero except
    the selected one) reproduces the base path's trailing zero-padding
    adds, so even signed-zero bins match.
    """
    from .chunkpolicy import note_variant
    sizes = policy.hist_sizes
    trips = policy.small_trips(cnt, sizes)
    note_variant("hist", sizes[0])
    out = leaf_hist_slice(part_bins, part_ghi, start, cnt,
                          num_bins=num_bins, row_chunk=sizes[0],
                          dtype=dtype, vary=vary, num_groups=num_groups,
                          cover=policy.base_cover(cnt, sizes))
    for w, trip in zip(sizes[1:], trips):
        note_variant("hist", w)
        out = out + leaf_hist_slice(
            part_bins, part_ghi, start, cnt, num_bins=num_bins,
            row_chunk=w, dtype=dtype, vary=vary, num_groups=num_groups,
            cover=trip)
    return out


# ----------------------------------------------------------------------
# Pallas TPU kernel
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_bins", "row_chunk",
                                             "use_bf16", "num_groups"))
def leaf_hist_pallas(part_bins, grad_p, hess_p, start, cnt, *,
                     num_bins: int, row_chunk: int, use_bf16: bool = False,
                     num_groups: int = 0):
    """Same contract as ``leaf_hist_slice`` (transposed (G, N_pad) binned
    input), as one Pallas kernel.

    A single program (grid=(1,)) walks the leaf's chunks with a dynamic trip
    count, double-buffered DMA from HBM, and per-feature one-hot matmuls
    (the bin axis is padded to a lane multiple so the MXU N dimension stays
    wide) accumulated into a VMEM scratch histogram — the TPU analog of the
    CUDA shared-memory per-block histograms
    (cuda_histogram_constructor.cu:18-460).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Gbuf, Np = part_bins.shape       # buffer rows (may be sublane-padded)
    G = num_groups or Gbuf           # real feature groups in the output
    C = row_chunk
    B = num_bins
    B128 = ((B + 127) // 128) * 128
    dtype = jnp.bfloat16 if use_bf16 else jnp.float32

    def kernel(start_ref, cnt_ref, bins_hbm, grad_hbm, hess_hbm, out_ref,
               bins_buf, grad_buf, hess_buf, acc_ref, sems):
        s0 = start_ref[0]
        total = cnt_ref[0]
        # chunk-ALIGNED windows covering [s0, s0+total): DMA starts must be
        # tile-aligned, leaf starts are arbitrary -> mask the partial edges
        c0 = jax.lax.div(s0, C)
        n_chunks = pl.cdiv(s0 + total, C) - c0
        acc_ref[:] = jnp.zeros_like(acc_ref)

        def get_copies(ci, slot):
            blk = c0 + ci
            return (
                pltpu.make_async_copy(
                    bins_hbm.at[:, blk], bins_buf.at[slot], sems.at[slot, 0]),
                pltpu.make_async_copy(
                    grad_hbm.at[blk], grad_buf.at[slot], sems.at[slot, 1]),
                pltpu.make_async_copy(
                    hess_hbm.at[blk], hess_buf.at[slot], sems.at[slot, 2]),
            )

        for c in get_copies(0, 0):
            c.start()

        def body(ci, _):
            slot = jax.lax.rem(ci, 2)

            @pl.when(ci + 1 < n_chunks)
            def _():
                for c in get_copies(ci + 1, 1 - slot):
                    c.start()

            for c in get_copies(ci, slot):
                c.wait()

            gpos = ((c0 + ci) * C +
                    jax.lax.broadcasted_iota(jnp.int32, (1, C), 1))
            valid = (gpos >= s0) & (gpos < s0 + total)
            g = jnp.where(valid, grad_buf[slot][None, :], 0.0)
            h = jnp.where(valid, hess_buf[slot][None, :], 0.0)
            gh = jnp.concatenate([g, h], axis=0).astype(dtype)    # (2, C)
            bins = bins_buf[slot].astype(jnp.int32)               # (G, C)
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (C, B128), 1)
            for f in range(G):
                oh = (bins[f][:, None] == iota_b).astype(dtype)   # (C, B128)
                part = jax.lax.dot_general(
                    gh, oh, dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)            # (2, B128)
                acc_ref[:, f, :] = acc_ref[:, f, :] + part
            return 0

        jax.lax.fori_loop(0, n_chunks, body, 0)
        out_ref[:] = acc_ref[:]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, Gbuf, C), part_bins.dtype),
            pltpu.VMEM((2, C), jnp.float32),
            pltpu.VMEM((2, C), jnp.float32),
            pltpu.VMEM((2, G, B128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
    )
    if Np % C:
        raise ValueError(f"N_pad={Np} must be a multiple of row_chunk={C}")
    nblocks = Np // C
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((2, G, B128), jnp.float32),
        grid_spec=grid_spec,
    )(jnp.asarray([start], jnp.int32), jnp.asarray([cnt], jnp.int32),
      part_bins.reshape(Gbuf, nblocks, C), grad_p.reshape(nblocks, C),
      hess_p.reshape(nblocks, C))
    return jnp.moveaxis(out[:, :, :B], 0, 2)
