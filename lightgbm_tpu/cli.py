"""Command-line application.

TPU-native re-implementation of the reference CLI (src/main.cpp,
src/application/application.{h,cpp}): `key=value` argv plus a `config=` file,
tasks train | predict | convert_model | refit | save_binary, plus two
framework-native tasks: `continual` — a deterministic drift drill
through the continual-training runtime (lightgbm_tpu/continual/):
drift is injected at a chosen tick, the regression must be detected, a
background retrain (killed once and resumed from checkpoint) hot-swaps
in, and a forced post-swap regression rolls back — the operator's
rehearsal that every continual failure path works on THIS install;
and `serve` — the production serving plane (lightgbm_tpu/serving/):
coalescing micro-batcher, multi-model registry with hot-swap/rollback,
per-tenant admission control, stdlib HTTP.

Usage:  python -m lightgbm_tpu task=train config=train.conf [key=value ...]
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .engine import train as _train
from .utils import log
from .utils.textio import load_text_file

__all__ = ["Application", "main"]


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse a reference-format config file: `key = value` lines, `#` comments
    (reference: application.cpp Application::LoadParameters / ConfigFile)."""
    out: Dict[str, str] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def parse_argv(argv: List[str]) -> Dict[str, str]:
    """reference: application.cpp Application(argc, argv):31-86 — argv
    `key=value` pairs override config-file values."""
    cli: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            log.warning("Unknown argument (expected key=value): %s", arg)
            continue
        k, v = arg.split("=", 1)
        cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    if "config" in cli:
        params.update(parse_config_file(cli["config"]))
    params.update(cli)  # command line overrides config file
    return params


class Application:
    """reference: src/application/application.h Application."""

    def __init__(self, argv: List[str]):
        self.raw_params = parse_argv(argv)
        self.config = Config(self.raw_params)

    def run(self) -> None:
        task = self.config.task
        # runtime telemetry (lightgbm_tpu/obs/): telemetry=counters|trace
        # arms the session before any device work; telemetry_out=DIR
        # exports JSONL + Chrome trace + Prometheus text when the task
        # finishes (even when it fails — the trace of a failed run is
        # the artifact an operator wants most)
        from . import obs
        obs.configure_from_config(self.config)
        # multi-host bootstrap before any device work (reference:
        # application.cpp:171 Network::Init ahead of LoadData/Train)
        from .parallel.network import init_from_config
        init_from_config(self.config)
        from .parallel.distributed import sync_config_params
        sync_config_params(self.config)
        try:
            if task == "train":
                self.train()
            elif task in ("predict", "prediction", "test"):
                self.predict()
            elif task == "convert_model":
                self.convert_model()
            elif task == "refit":
                self.refit()
            elif task == "save_binary":
                self.save_binary()
            elif task == "continual":
                self.continual()
            elif task == "serve":
                self.serve()
            else:
                log.fatal("Unknown task: %s", task)
        finally:
            if self.config.telemetry_out and obs.enabled():
                # never let a failed export mask the task's own error
                # (e.g. an unwritable telemetry_out during a training
                # failure must not replace the training exception)
                try:
                    obs.memory_snapshot()
                    paths = obs.export_session(self.config.telemetry_out)
                    log.info("telemetry exported: %s",
                             ", ".join(sorted(paths.values())))
                except OSError as exc:
                    log.warning("telemetry export to %s failed: %s",
                                self.config.telemetry_out, exc)

    # ------------------------------------------------------------------
    @staticmethod
    def _side_file(path: str, suffix: str):
        """Reference-style side files next to the data file
        (dataset_loader.cpp LoadQueryBoundaries / LoadWeights /
        LoadInitialScore: ``<data>.query`` etc.)."""
        import numpy as np
        p = path + "." + suffix
        if os.path.exists(p):
            return np.loadtxt(p, dtype=np.float64, ndmin=1)
        return None

    def _load_train_data(self) -> Dataset:
        cfg = self.config
        if not cfg.data:
            log.fatal("No training data file specified (data=)")
        from .dataset import BinnedDataset
        if BinnedDataset.is_binary_file(cfg.data):
            # binary fast path (reference: LoadFromBinFile,
            # dataset_loader.cpp:417)
            return Dataset(cfg.data, params=dict(self.raw_params))
        loaded = load_text_file(
            cfg.data, has_header=cfg.header, label_column=cfg.label_column,
            weight_column=cfg.weight_column, group_column=cfg.group_column,
            ignore_column=cfg.ignore_column)
        group = loaded.group
        if group is None:
            group = self._side_file(cfg.data, "query")
        weight = loaded.weight
        if weight is None:
            weight = self._side_file(cfg.data, "weight")
        init = self._side_file(cfg.data, "init")
        ds = Dataset(loaded.X, label=loaded.label, weight=weight,
                     group=group, init_score=init,
                     feature_name=loaded.feature_names or "auto",
                     params=dict(self.raw_params))
        return ds

    def train(self) -> None:
        cfg = self.config
        train_set = self._load_train_data()
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if cfg.valid:
            for i, vf in enumerate(str(cfg.valid).split(",")):
                vf = vf.strip()
                if not vf:
                    continue
                vl = load_text_file(
                    vf, has_header=cfg.header, label_column=cfg.label_column,
                    weight_column=cfg.weight_column,
                    group_column=cfg.group_column,
                    ignore_column=cfg.ignore_column)
                vgroup = vl.group if vl.group is not None \
                    else self._side_file(vf, "query")
                vweight = vl.weight if vl.weight is not None \
                    else self._side_file(vf, "weight")
                valid_sets.append(Dataset(
                    vl.X, label=vl.label, weight=vweight, group=vgroup,
                    init_score=self._side_file(vf, "init"),
                    reference=train_set, params=dict(self.raw_params)))
                valid_names.append(os.path.basename(vf))
        init_model = cfg.input_model or None
        callbacks = None
        if cfg.snapshot_freq and cfg.snapshot_freq > 0:
            # periodic model snapshots (reference: GBDT::Train,
            # gbdt.cpp:244-248 — "<output_model>.snapshot_iter_<i>"),
            # written atomically (temp + rename) so a crash mid-write
            # never leaves a truncated model file behind
            freq = int(cfg.snapshot_freq)
            out_path = cfg.output_model

            def _snapshot(env):
                it = env.iteration + 1
                if it % freq == 0:
                    final = f"{out_path}.snapshot_iter_{it}"
                    tmp = f"{final}.tmp{os.getpid()}"
                    env.model.save_model(tmp)
                    os.replace(tmp, final)

            _snapshot.order = 100
            callbacks = [_snapshot]
        if cfg.checkpoint_dir and not cfg.checkpoint_interval:
            log.warning("checkpoint_dir is set but checkpoint_interval is "
                        "0; no training checkpoints will be written (set "
                        "checkpoint_interval=N to checkpoint every N "
                        "iterations)")
        if cfg.checkpoint_resume:
            log.info("checkpoint_resume=true: will resume from the latest "
                     "checkpoint under %s if one exists", cfg.checkpoint_dir)
        if cfg.is_provide_training_metric:
            # reference: training_metric adds the train set to the
            # evaluated sets (Application::LoadData train_metric path)
            valid_sets = [train_set] + valid_sets
            valid_names = ["training"] + valid_names
        if valid_sets or cfg.is_provide_training_metric:
            # periodic metric output every metric_freq iterations
            # (reference: Application::Train -> Boosting::Train
            # OutputMetric cadence, config.h metric_freq)
            from .callback import log_evaluation
            callbacks = (callbacks or []) + [
                log_evaluation(period=max(int(cfg.metric_freq), 1))]
        booster = _train(dict(self.raw_params), train_set,
                         num_boost_round=cfg.num_iterations,
                         valid_sets=valid_sets or None,
                         valid_names=valid_names or None,
                         init_model=init_model,
                         callbacks=callbacks)
        booster.save_model(cfg.output_model)
        log.info("Finished training; model saved to %s", cfg.output_model)
        # model/data-health artifact (obs/health.py): the flight
        # recorder + reference profile + skew digests of THIS run,
        # next to the telemetry exports
        from .obs import health as obs_health
        if obs_health.enabled() and cfg.telemetry_out:
            import json as _json
            try:
                os.makedirs(cfg.telemetry_out, exist_ok=True)
                out = os.path.join(cfg.telemetry_out, "health.json")
                with open(out, "w") as fh:
                    _json.dump(booster.health_report(), fh, indent=1,
                               default=str)
                log.info("health report exported: %s", out)
            except OSError as exc:
                log.warning("health export to %s failed: %s",
                            cfg.telemetry_out, exc)

    def predict(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log.fatal("task=predict requires input_model=")
        if not cfg.data:
            log.fatal("task=predict requires data=")
        booster = Booster(model_file=cfg.input_model)
        loaded = load_text_file(
            cfg.data, has_header=cfg.header, label_column=cfg.label_column,
            ignore_column=cfg.ignore_column)
        preds = booster.predict(
            loaded.X, raw_score=bool(cfg.predict_raw_score),
            pred_leaf=bool(cfg.predict_leaf_index),
            pred_contrib=bool(cfg.predict_contrib),
            start_iteration=int(cfg.start_iteration_predict),
            num_iteration=cfg.num_iteration_predict,
            predict_disable_shape_check=bool(
                cfg.predict_disable_shape_check),
            pred_early_stop=bool(cfg.pred_early_stop),
            pred_early_stop_freq=int(cfg.pred_early_stop_freq),
            pred_early_stop_margin=float(cfg.pred_early_stop_margin))
        preds = np.asarray(preds)
        with open(cfg.output_result, "w") as fh:
            if preds.ndim == 1:
                fh.write("\n".join(repr(float(v)) for v in preds))
            else:
                fh.write("\n".join("\t".join(repr(float(v)) for v in row)
                                   for row in preds))
            fh.write("\n")
        log.info("Finished prediction; results saved to %s", cfg.output_result)

    def refit(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log.fatal("task=refit requires input_model=")
        booster = Booster(model_file=cfg.input_model)
        loaded = load_text_file(
            cfg.data, has_header=cfg.header, label_column=cfg.label_column,
            weight_column=cfg.weight_column, group_column=cfg.group_column,
            ignore_column=cfg.ignore_column)
        extra = {k: v for k, v in self.raw_params.items()
                 if k not in ("task", "config", "data", "input_model",
                              "output_model", "valid")}
        new_booster = booster.refit(loaded.X, loaded.label,
                                    weight=loaded.weight, group=loaded.group,
                                    decay_rate=cfg.refit_decay_rate, **extra)
        new_booster.save_model(cfg.output_model)
        log.info("Finished refit; model saved to %s", cfg.output_model)

    def continual(self) -> None:
        """Run the deterministic continual-training drift drill (see the
        module docstring) with this config's ``continual_*`` parameters;
        one JSON line per scenario, non-zero exit on a broken invariant.
        ``checkpoint_dir=`` roots the retrain checkpoints (a temp
        directory otherwise)."""
        import json
        import shutil
        import tempfile

        from .continual import run_drift_drill

        cfg = self.config
        work = cfg.checkpoint_dir or tempfile.mkdtemp(prefix="continual-")
        own_tmp = not cfg.checkpoint_dir
        # the drill's synthetic stream is regression-shaped; IO/model
        # params don't apply to it
        _skip = {"task", "config", "objective", "num_class", "data",
                 "valid", "input_model", "output_model", "metric"}
        overrides = {k: v for k, v in self.raw_params.items()
                     if Config.canonical_name(k) is not None
                     and Config.canonical_name(k) not in _skip}
        problems = []
        try:
            for scenario in ("swap", "degrade", "rollback"):
                rep = run_drift_drill(
                    scenario, params=overrides,
                    checkpoint_dir=work if scenario == "swap" else None)
                rep.pop("ticks", None)
                print(json.dumps({"scenario": scenario, "report": {
                    k: v for k, v in rep.items() if k != "history"}}))
                if scenario == "swap" and not (
                        rep.get("detected_within_window")
                        and rep.get("one_trace_per_key")
                        and rep.get("swap_tick") is not None):
                    problems.append("swap drill failed")
                if scenario == "degrade" and not rep.get("still_serving"):
                    problems.append("degrade drill failed")
                if scenario == "rollback" and not (
                        rep.get("rollback_within")
                        and rep.get("pre_post_identical")):
                    problems.append("rollback drill failed")
        finally:
            if own_tmp:
                shutil.rmtree(work, ignore_errors=True)
        if problems:
            log.fatal("continual drill: %s", "; ".join(problems))
        log.info("continual drill passed: detection, checkpointed "
                 "retrain, guarded swap, degradation and rollback all "
                 "exercised")

    def serve(self) -> None:
        """Run the production serving plane (lightgbm_tpu/serving/):
        coalescing micro-batcher over the device ServingEngine,
        multi-model registry with hot-swap/rollback endpoints, and
        per-tenant admission control, behind a stdlib HTTP server.
        Models: ``serve_models=name=path[,...]`` or ``input_model=``
        (published as ``default``); see the ``serve_*`` parameter
        family and README "Serving service"."""
        from .serving.httpd import run_serve_task
        run_serve_task(self.config)

    def save_binary(self) -> None:
        cfg = self.config
        ds = self._load_train_data()
        ds.construct(dict(self.raw_params))
        out = cfg.data + ".bin"
        ds.save_binary(out)
        log.info("Saved binary dataset to %s", out)

    def convert_model(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            log.fatal("task=convert_model requires input_model=")
        language = cfg.convert_model_language or "cpp"
        if language not in ("cpp", "c++"):
            log.fatal("Only convert_model_language=cpp is supported")
        booster = Booster(model_file=cfg.input_model)
        code = model_to_cpp(booster)
        with open(cfg.convert_model, "w") as fh:
            fh.write(code)
        log.info("Converted model written to %s", cfg.convert_model)


def model_to_cpp(booster: Booster) -> str:
    """Generate standalone C++ if-else prediction code from a model
    (reference: gbdt_model_text.cpp GBDT::ModelToIfElse)."""
    g = booster._gbdt
    K = g.num_tree_per_iteration
    out: List[str] = [
        "// Generated by lightgbm_tpu task=convert_model",
        "#include <cmath>",
        "#include <cstring>",
        "",
        f"static const int kNumClass = {g.num_class};",
        f"static const int kNumTreePerIteration = {K};",
        f"static const int kMaxFeatureIdx = {g.max_feature_idx};",
        "",
    ]

    def emit_node(tree, nid: int, depth: int, lines: List[str]) -> None:
        ind = "  " * depth
        if nid < 0:
            leaf = ~nid
            lines.append(f"{ind}return {float(tree.leaf_value[leaf])!r};")
            return
        f = int(tree.split_feature[nid])
        cat, default_left, _missing = tree.unpack_decision_type(
            int(tree.decision_type[nid]))
        if cat:
            cats = tree.cat_threshold_values(nid) \
                if hasattr(tree, "cat_threshold_values") else []
            cond = " || ".join(f"fval == {c}.0" for c in cats) or "false"
            lines.append(f"{ind}{{ const double fval = arr[{f}];")
            lines.append(f"{ind}if (!std::isnan(fval) && ({cond})) {{")
        else:
            thr = float(tree.threshold[nid])
            lines.append(f"{ind}{{ const double fval = arr[{f}];")
            if default_left:
                lines.append(
                    f"{ind}if (std::isnan(fval) || fval <= {thr!r}) {{")
            else:
                lines.append(
                    f"{ind}if (!std::isnan(fval) && fval <= {thr!r}) {{")
        emit_node(tree, int(tree.left_child[nid]), depth + 1, lines)
        lines.append(f"{ind}}} else {{")
        emit_node(tree, int(tree.right_child[nid]), depth + 1, lines)
        lines.append(f"{ind}}} }}")

    for i, tree in enumerate(g.models):
        out.append(f"static double PredictTree{i}(const double* arr) {{")
        body: List[str] = []
        if tree.num_leaves <= 1:
            body.append(f"  return {float(tree.leaf_value[0])!r};")
        else:
            emit_node(tree, 0, 1, body)
        out.extend(body)
        out.append("}")
        out.append("")

    out.append("void Predict(const double* features, double* output) {")
    out.append(f"  for (int k = 0; k < kNumTreePerIteration; ++k) "
               f"output[k] = 0.0;")
    for i in range(len(g.models)):
        out.append(f"  output[{i % K}] += PredictTree{i}(features);")
    out.append("}")
    out.append("")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    # honor an explicit platform pin even when a site plugin force-registers
    # another backend and overrides the env var during jax init; plugin
    # platform aliases (e.g. a tunnel) are left for init-time resolution
    if os.environ.get("JAX_PLATFORMS") in ("cpu", "tpu"):
        import jax
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    if argv is None:
        argv = sys.argv[1:]
    app = Application(argv)
    app.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
