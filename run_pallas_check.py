"""Standalone TPU check of the packed partition kernel vs the NumPy oracle
(same cases as tests/test_pallas_tpu.py, runnable outside the CPU-pinned
pytest conftest)."""
import numpy as np, jax, jax.numpy as jnp
assert jax.default_backend() == "tpu", jax.default_backend()
from lightgbm_tpu.ops.partition_pallas import (partition_leaf_pallas,
                                               make_scalars, SC_ROWS)

def oracle(pb, pg, start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl):
    pb = pb.copy(); pg = pg.copy()
    colv = pb[col, start:start+cnt].astype(np.int32)
    fb_raw = colv - bstart
    in_r = (fb_raw >= 1) & (fb_raw <= nb - 1)
    fb = np.where(isb == 1, np.where(in_r, fb_raw, dbin), colv)
    miss = (fb == dbin) if mtype == 1 else ((fb == nb-1) if mtype == 2 else np.zeros_like(fb, bool))
    gl = np.where(miss, dl != 0, fb <= thr)
    order = np.concatenate([np.where(gl)[0], np.where(~gl)[0]]) + start
    pb[:, start:start+cnt] = pb[:, order]
    pg[:, start:start+cnt] = pg[:, order]
    return pb, pg, int(gl.sum())

C, G32 = 1024, 32
Np = 10 * C
rng = np.random.RandomState(7)
for trial in range(8):
    pb = rng.randint(0, 250, (G32, Np)).astype(np.uint8)
    pg = rng.randn(8, Np).astype(np.float32)
    start = int(rng.randint(C, 5*C)); cnt = int(rng.randint(0, 4*C))
    col = int(rng.randint(0, 28)); isb = int(rng.rand() < 0.3)
    nb = int(rng.randint(10, 250)); bstart = int(rng.randint(0, 5)) if isb else 0
    dbin = int(rng.randint(0, nb)); mtype = int(rng.randint(0, 3))
    thr = int(rng.randint(0, nb)); dl = int(rng.rand() < 0.5)
    epb, epg, enl = oracle(pb, pg, start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl)
    sc = make_scalars(start, cnt, col, bstart, isb, nb, dbin, mtype, thr, dl)
    rpb, rpg, _, rnl = partition_leaf_pallas(
        jnp.asarray(pb), jnp.asarray(pg), jnp.zeros((SC_ROWS, Np), jnp.int32),
        sc, row_chunk=C)
    assert int(np.asarray(rnl)[0,0]) == enl, (trial, int(np.asarray(rnl)[0,0]), enl)
    np.testing.assert_array_equal(np.asarray(rpb), epb)
    np.testing.assert_array_equal(np.asarray(rpg)[:3].view(np.int32), epg[:3].view(np.int32))
    print("trial", trial, "ok", flush=True)
print("ALL OK")
